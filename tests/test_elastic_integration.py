"""End-to-end elastic integration on localhost — real worker processes, a
scripted discovery source whose output changes mid-run, full re-rendezvous.

Mirrors the reference's ``test/integration/elastic_common.py`` design
(discovery scripts whose output changes over time, elastic_common.py:33-52,
host add/remove runs :118-246), on the JAX CPU multi-process world.
"""

import os
import sys
import threading
import time

import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("HVD_TPU_SKIP_MULTIPROC") == "1",
    reason="multi-process tier disabled")


WORKER_SRC = r"""
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import horovod_tpu as hvd

TOTAL = int(os.environ["TEST_TOTAL_BATCHES"])
OUT = os.environ["TEST_OUT_DIR"]

hvd.init()

CRASH_RANK = int(os.environ.get("TEST_CRASH_RANK", "-1"))
CRASH_BATCH = int(os.environ.get("TEST_CRASH_BATCH", "-1"))
CRASH_MARKER = os.path.join(OUT, "crashed.marker")
CHAINED = os.environ.get("TEST_CHAINED") == "1"


def _maybe_crash(batch):
    if (batch == CRASH_BATCH and hvd.rank() == CRASH_RANK
            and not os.path.exists(CRASH_MARKER)):
        with open(CRASH_MARKER, "w") as f:
            f.write(str(os.getpid()))
        os._exit(137)  # simulated hard crash (SIGKILL-style)


if CHAINED:
    # The no-host-block optimizer path: a peer crash surfaces at
    # state.commit()'s device_get (translated to HorovodInternalError),
    # NOT inside any engine wait — the dataflow-chained elastic scenario.
    import jax.numpy as jnp
    import optax
    from horovod_tpu.optimizer import DistributedEagerOptimizer

    w0 = {"w": np.ones(4, np.float32)}
    state = hvd.elastic.TPUState(params=w0,
                                 opt_state=optax.sgd(0.05).init(w0),
                                 batch=0)

    @hvd.elastic.run
    def train(state):
        opt = DistributedEagerOptimizer(optax.sgd(0.05))
        grad_fn = jax.jit(jax.grad(lambda p, x: jnp.sum((p["w"] * x) ** 2)))
        while state.batch < TOTAL:
            _maybe_crash(state.batch)
            p = jax.tree_util.tree_map(jnp.asarray, state.params)
            o = jax.tree_util.tree_map(jnp.asarray, state.opt_state)
            p, o = opt.update_and_apply(grad_fn(p, jnp.ones(4)), o, p)
            state.params, state.opt_state = p, o
            state.batch += 1
            state.commit()
            time.sleep(float(os.environ.get("TEST_BATCH_SLEEP", "0.1")))
        return {"rank": hvd.rank(), "size": hvd.size(),
                "batch": state.batch,
                "w0": float(np.asarray(state.params["w"])[0])}
else:
    state = hvd.elastic.ObjectState(batch=0)

    @hvd.elastic.run
    def train(state):
        while state.batch < TOTAL:
            _maybe_crash(state.batch)
            out = np.asarray(hvd.allreduce(np.ones(2), name=f"b{state.batch}",
                                           op=hvd.Sum))
            assert out[0] == hvd.size(), (out, hvd.size())
            state.batch += 1
            state.commit()
            time.sleep(float(os.environ.get("TEST_BATCH_SLEEP", "0.1")))
        return {"rank": hvd.rank(), "size": hvd.size(), "batch": state.batch}


result = train(state)
if result is not None:
    path = os.path.join(OUT, f"done_{result['rank']}_{os.getpid()}.json")
    with open(path, "w") as f:
        json.dump(result, f)
else:
    path = os.path.join(OUT, f"removed_{os.getpid()}.json")
    with open(path, "w") as f:
        json.dump({"removed": True}, f)
hvd.shutdown()
"""


def _worker_env(tmp_path, total, sleep="0.1", extra=None):
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": repo_root + os.pathsep + env.get("PYTHONPATH", ""),
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "HOROVOD_STALL_CHECK_DISABLE": "1",
        "HOROVOD_GLOO_TIMEOUT_SECONDS": "90",
        "HOROVOD_TPU_HEARTBEAT_TIMEOUT": "5",
        "HOROVOD_TPU_SHUTDOWN_TIMEOUT": "10",
        "TEST_OUT_DIR": str(tmp_path / "out"),
        "TEST_TOTAL_BATCHES": str(total),
        "TEST_BATCH_SLEEP": sleep,
    })
    env.update(extra or {})
    return env


def _launch(tmp_path, hosts_text, np_, max_np, total_batches, extra_env=None):
    from horovod_tpu.elastic.discovery import HostDiscoveryScript
    from horovod_tpu.elastic.launcher import launch_elastic_job

    hostsfile = tmp_path / "hosts.txt"
    hostsfile.write_text(hosts_text)
    script = tmp_path / "train.py"
    script.write_text(WORKER_SRC)
    (tmp_path / "out").mkdir()

    discovery = HostDiscoveryScript(f"cat {hostsfile}")
    env = _worker_env(tmp_path, total_batches, extra=extra_env)
    errors = []
    driver_box = []
    driver_ready = threading.Event()

    def _grab_driver(d):
        driver_box.append(d)
        driver_ready.set()

    def _run():
        try:
            launch_elastic_job(discovery, np_, [sys.executable, str(script)],
                               base_env=env, min_np=np_, max_np=max_np,
                               timeout=120, driver_callback=_grab_driver)
        except Exception as e:  # surfaced in the asserting test thread
            errors.append(e)

    t = threading.Thread(target=_run, daemon=True)
    t.start()
    assert driver_ready.wait(timeout=60), "driver never constructed"
    return hostsfile, t, errors, driver_box[0]


def _set_hosts(hostsfile, text):
    # atomic replace: a plain write_text truncates first, and the discovery
    # script (`cat`) can race the window and see an empty host list
    import os as _os
    tmp = hostsfile.with_suffix(".tmp")
    tmp.write_text(text)
    _os.replace(tmp, hostsfile)


def _done_results(tmp_path):
    import json
    out = tmp_path / "out"
    results = []
    for p in sorted(out.glob("done_*.json")):
        with open(p) as f:
            results.append(json.load(f))
    return results


# Tier-1 budget (ISSUE 9 satellite, profiled with --durations=25): the
# four real-process elastic runs cost ~380s together — far past the 870s
# suite budget. test_elastic_scale_down stays in tier-1 as the
# subsystem's multiprocess representative (it exercises world formation,
# resize, AND clean worker removal in one 47s run, next to
# test_multiprocess.py::test_run_elastic_programmatic); the hard-kill
# recovery runs are real-process kills — the slow marker's own category
# — and their recovery semantics run deterministically in tier-1 via the
# chaos suite (watchdog hang -> restore -> finish, no process killed).
@pytest.mark.slow
@pytest.mark.integration
def test_elastic_scale_up(tmp_path):
    """2 workers start; a third slot appears mid-run; all finish at size 3."""
    hostsfile, t, errors, driver = _launch(tmp_path, "localhost:2\n",
                                           np_=2, max_np=3,
                                           total_batches=150)
    # event-driven: add the slot only once the first world is fully formed
    # (VERDICT r2 item 4 — no sleep margins)
    assert driver.wait_for_world(1, timeout=120), "initial world never formed"
    _set_hosts(hostsfile, "localhost:3\n")
    t.join(timeout=300)
    assert not t.is_alive(), "elastic job did not finish"
    assert not errors, errors
    results = _done_results(tmp_path)
    assert len(results) == 3, results
    assert all(r["size"] == 3 for r in results), results
    assert all(r["batch"] == 150 for r in results), results
    assert sorted(r["rank"] for r in results) == [0, 1, 2]


@pytest.mark.integration
def test_elastic_scale_down(tmp_path):
    """3 workers start; one slot is scaled away mid-run; the removed worker
    exits cleanly and the remaining two finish at size 2."""
    hostsfile, t, errors, driver = _launch(tmp_path, "localhost:3\n",
                                           np_=2, max_np=3,
                                           total_batches=150)
    assert driver.wait_for_world(1, timeout=120), "initial world never formed"
    _set_hosts(hostsfile, "localhost:2\n")
    t.join(timeout=300)
    assert not t.is_alive(), "elastic job did not finish"
    assert not errors, errors
    results = _done_results(tmp_path)
    assert len(results) == 2, results
    assert all(r["size"] == 2 for r in results), results
    assert all(r["batch"] == 150 for r in results), results
    removed = list((tmp_path / "out").glob("removed_*.json"))
    assert len(removed) == 1, removed


@pytest.mark.slow
@pytest.mark.integration
def test_elastic_crash_recovery(tmp_path):
    """A worker is hard-killed mid-run (no graceful exit). Survivors see the
    failed collective as HorovodInternalError, restore committed state
    in-process, re-rendezvous, and — with the crashed slot relaunched by the
    driver — the job completes at full size with no lost progress.

    Mirrors the reference's single-rank-failure elastic integration runs
    (test/integration/elastic_common.py:145-212) and closes the ADVICE r1
    finding that only membership changes, never crashes, were exercised."""
    hostsfile, t, errors, _driver = _launch(
        tmp_path, "localhost:3\n", np_=3, max_np=3, total_batches=60,
        extra_env={"TEST_CRASH_RANK": "2", "TEST_CRASH_BATCH": "20"})
    t.join(timeout=240)
    assert not t.is_alive(), "elastic job did not finish"
    assert not errors, errors
    assert os.path.exists(str(tmp_path / "out" / "crashed.marker")), \
        "the designated worker never crashed"
    results = _done_results(tmp_path)
    assert len(results) == 3, results
    assert all(r["size"] == 3 for r in results), results
    # no lost progress: every worker finished the full batch count, and the
    # job completed despite the hard kill
    assert all(r["batch"] == 60 for r in results), results
    assert sorted(r["rank"] for r in results) == [0, 1, 2]


@pytest.mark.slow
@pytest.mark.integration
def test_elastic_crash_recovery_chained_optimizer(tmp_path):
    """Same hard-kill scenario, but the training loop is the r4
    dataflow-chained DistributedEagerOptimizer (zero host blocks inside
    engine code): survivors first see the dead peer at commit()'s
    device_get, which TPUState translates to HorovodInternalError — the
    elastic loop must still restore, re-rendezvous, and finish at full
    size with consistent replicas."""
    hostsfile, t, errors, _driver = _launch(
        tmp_path, "localhost:3\n", np_=3, max_np=3, total_batches=40,
        extra_env={"TEST_CRASH_RANK": "2", "TEST_CRASH_BATCH": "12",
                   "TEST_CHAINED": "1"})
    t.join(timeout=240)
    assert not t.is_alive(), "elastic job did not finish"
    assert not errors, errors
    assert os.path.exists(str(tmp_path / "out" / "crashed.marker")), \
        "the designated worker never crashed"
    results = _done_results(tmp_path)
    assert len(results) == 3, results
    assert all(r["size"] == 3 for r in results), results
    assert all(r["batch"] == 40 for r in results), results
    # replicas agree after recovery (averaged grads + committed state)
    w0s = {round(r["w0"], 6) for r in results}
    assert len(w0s) == 1, results
