"""Task-service RPC tests (reference test/test_service.py: the HMAC socket
services — here the signed JSON-over-HTTP redesign) + NIC discovery."""

import sys
import time
import urllib.error

import pytest

from horovod_tpu.runner.service import (TaskClient, TaskService,
                                        candidate_driver_ips, make_secret_key,
                                        resolve_driver_ip)


@pytest.fixture
def service():
    key = make_secret_key()
    svc = TaskService(key, addr=("127.0.0.1", 0))
    svc.start()
    yield svc, key
    svc.stop()


def _client(svc, key):
    return TaskClient(f"127.0.0.1:{svc.port}", key)


def test_run_and_wait(service):
    svc, key = service
    c = _client(svc, key)
    assert c.run_command([sys.executable, "-c", "print('hi'); exit(7)"]) == \
        {"started": True}
    assert c.wait_for_command_exit_code(timeout=30) == 7


def test_env_passthrough(service):
    svc, key = service
    c = _client(svc, key)
    c.run_command([sys.executable, "-c",
                   "import os, sys; sys.exit(int(os.environ['T_CODE']))"],
                  env={"T_CODE": "5"})
    assert c.wait_for_command_exit_code(timeout=30) == 5


def test_abort(service):
    svc, key = service
    c = _client(svc, key)
    c.run_command([sys.executable, "-c", "import time; time.sleep(60)"])
    time.sleep(0.5)
    assert c.abort_command()["aborted"] is True
    code = c.wait_for_command_exit_code(timeout=30)
    assert code != 0


def test_second_command_rejected_while_running(service):
    svc, key = service
    c = _client(svc, key)
    c.run_command([sys.executable, "-c", "import time; time.sleep(30)"])
    time.sleep(0.3)
    assert c.run_command(["true"])["started"] is False
    c.abort_command()


def test_bad_signature_rejected(service):
    svc, key = service
    bad = TaskClient(f"127.0.0.1:{svc.port}", make_secret_key())
    with pytest.raises(urllib.error.HTTPError) as ei:
        bad.command_exit_code()
    assert ei.value.code == 401
    # the service remains usable with the right key
    assert _client(svc, key).command_exit_code()["running"] is False


def test_unknown_verb_404(service):
    svc, key = service
    c = _client(svc, key)
    with pytest.raises(urllib.error.HTTPError) as ei:
        c._call("no_such_verb", {})
    assert ei.value.code == 404


def test_probe_reachability(service):
    svc, key = service
    c = _client(svc, key)
    # the service binds 127.0.0.1 only, so the 127.0.0.2 loopback alias is
    # refused (an external unreachable IP can't be used here: the sandbox's
    # egress proxy accepts any outbound connect)
    reach = c.probe(["127.0.0.1", "127.0.0.2"], svc.port)
    assert reach == ["127.0.0.1"]


def test_candidate_driver_ips_always_has_fallback():
    cands = candidate_driver_ips()
    assert cands
    assert cands[-1] == "127.0.0.1"


def test_resolve_driver_ip_intersection(service):
    svc, key = service
    c = _client(svc, key)
    # with a real probe against our own service port, loopback is always in
    # the intersection
    ip = resolve_driver_ip([c], svc.port)
    assert ip in candidate_driver_ips()


def test_resolve_driver_ip_no_agreement():
    class FakeClient:
        def probe(self, addresses, port):
            return []
    with pytest.raises(RuntimeError, match="reachable by every worker"):
        resolve_driver_ip([FakeClient()], 1234)


@pytest.mark.integration
def test_launch_via_task_agents_end_to_end(tmp_path):
    """Two local task agents (standing in for two hosts) run a real
    2-process collective job dispatched through the signed RPC channel —
    the reference's task-server launch flow (driver_service.py:48 +
    task_service RunCommand) without ssh."""
    import os
    from horovod_tpu.runner.launch import launch_via_task_agents

    key = make_secret_key()
    # distinct hostnames so the rendezvous slots don't collide
    a0 = TaskService(key, addr=("127.0.0.1", 0)); a0.start()
    a1 = TaskService(key, addr=("127.0.0.1", 0)); a1.start()
    out = tmp_path / "out"
    out.mkdir()
    script = tmp_path / "w.py"
    script.write_text(
        "import os, json\n"
        "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import numpy as np\n"
        "import horovod_tpu as hvd\n"
        "hvd.init()\n"
        "v = np.asarray(hvd.allreduce(np.ones(2), name='t', op=hvd.Sum))\n"
        "p = os.path.join(os.environ['T_OUT'], f'r{hvd.rank()}.json')\n"
        "json.dump({'sum': float(v[0]), 'size': hvd.size()}, open(p, 'w'))\n"
        "hvd.shutdown()\n")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {
        "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "HOROVOD_STALL_CHECK_DISABLE": "1",
        "T_OUT": str(out),
    }
    try:
        launch_via_task_agents(
            [f"127.0.0.1:{a0.port}", f"localhost:{a1.port}"], key, np=2,
            command=[sys.executable, str(script)], base_env=env, timeout=120)
    finally:
        a0.stop()
        a1.stop()
    import json
    results = [json.load(open(out / f"r{r}.json")) for r in range(2)]
    assert all(r == {"sum": 2.0, "size": 2} for r in results), results


def test_replayed_request_to_other_verb_rejected(service):
    """The MAC binds the verb: a captured signature for one verb cannot be
    re-sent to another (review r2 security finding)."""
    import json as _json
    import time as _time
    import urllib.request
    from horovod_tpu.runner.service import SIG_HEADER, TS_HEADER, _sign
    svc, key = service
    body = _json.dumps({}).encode()
    ts = repr(_time.time())
    sig = _sign(key, "command_exit_code", ts, body)
    req = urllib.request.Request(
        f"http://127.0.0.1:{svc.port}/abort_command", data=body,
        method="POST", headers={SIG_HEADER: sig, TS_HEADER: ts})
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=5)
    assert ei.value.code == 401


def test_stale_timestamp_rejected(service):
    import json as _json
    import urllib.request
    from horovod_tpu.runner.service import SIG_HEADER, TS_HEADER, _sign
    svc, key = service
    body = _json.dumps({}).encode()
    ts = repr(1.0)  # 1970
    sig = _sign(key, "command_exit_code", ts, body)
    req = urllib.request.Request(
        f"http://127.0.0.1:{svc.port}/command_exit_code", data=body,
        method="POST", headers={SIG_HEADER: sig, TS_HEADER: ts})
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=5)
    assert ei.value.code == 401


def test_launch_error_surfaced(service):
    """A nonexistent binary is an immediate, attributable error, not a
    timeout (review r2 finding)."""
    svc, key = service
    c = _client(svc, key)
    c.run_command(["/no/such/binary-xyz"])
    with pytest.raises(RuntimeError, match="failed to launch"):
        c.wait_for_command_exit_code(timeout=20)


def test_same_host_agents_get_distinct_local_ranks(tmp_path):
    """Two agents on one hostname must become local ranks 0 and 1, not two
    colliding (host, 0) slots (review r2 finding)."""
    import os
    from horovod_tpu.runner.launch import launch_via_task_agents
    key = make_secret_key()
    a0 = TaskService(key, addr=("127.0.0.1", 0)); a0.start()
    a1 = TaskService(key, addr=("127.0.0.1", 0)); a1.start()
    out = tmp_path / "o"; out.mkdir()
    script = tmp_path / "w.py"
    script.write_text(
        "import os, json\n"
        "lr = os.environ['HOROVOD_LOCAL_RANK']\n"
        "open(os.path.join(os.environ['T_OUT'], 'lr_' + lr), 'w').write(lr)\n")
    env = {"T_OUT": str(out),
           "PYTHONPATH": os.path.dirname(os.path.dirname(
               os.path.abspath(__file__)))}
    launch_via_task_agents(
        [f"127.0.0.1:{a0.port}", f"127.0.0.1:{a1.port}"], key, np=2,
        command=[sys.executable, str(script)], base_env=env, timeout=60)
    a0.stop(); a1.stop()
    assert sorted(p.name for p in out.iterdir()) == ["lr_0", "lr_1"]


def test_replayed_request_rejected():
    """A verbatim re-send of a captured signed request must be rejected
    inside the freshness window (ADVICE r2 replay finding)."""
    import time
    import urllib.request
    import urllib.error
    from horovod_tpu.runner.service import (TaskService, make_secret_key,
                                            _sign, SIG_HEADER, TS_HEADER)

    key = make_secret_key()
    svc = TaskService(key, addr=("127.0.0.1", 0))
    svc.start()
    try:
        port = svc.port if hasattr(svc, "port") else \
            svc._httpd.server_address[1]
        url = f"http://127.0.0.1:{port}/probe"
        body = b"{}"
        ts = str(time.time())
        sig = _sign(key, "probe", ts, body)

        def send():
            req = urllib.request.Request(url, data=body, method="POST")
            req.add_header(SIG_HEADER, sig)
            req.add_header(TS_HEADER, ts)
            try:
                with urllib.request.urlopen(req, timeout=5) as r:
                    return r.status
            except urllib.error.HTTPError as e:
                return e.code

        first = send()
        replay = send()
        assert first != 401, "legitimate signed request rejected"
        assert replay == 401, "replayed request accepted"
    finally:
        svc.stop()
