"""The SPMD divergence checker (ISSUE 11 tentpole): every finding class
must be detected with file:line on the known fixtures, the clean fixture
must produce zero findings, and the live ``horovod_tpu/`` tree must be
clean with every suppression and agreed site carrying its reason.
"""

import os

import pytest

from horovod_tpu.analysis import divcheck

pytestmark = pytest.mark.lint

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "divcheck")
PKG_ROOT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "horovod_tpu")


def _check_fixture(name):
    path = os.path.join(FIXTURES, name)
    rep = divcheck.check_paths([path], root=FIXTURES)
    lines = []
    if os.path.isfile(path):
        lines = open(path).read().splitlines()
    return rep, lines


def _line_of(lines, needle, nth=0):
    hits = [i + 1 for i, l in enumerate(lines) if needle in l]
    assert hits, f"fixture drifted: {needle!r} not found"
    return hits[nth]


class TestViolationClasses:
    def test_rank_gated_collective(self):
        rep, lines = _check_fixture("bad_rank_gated.py")
        got = {(f.check, f.line) for f in rep.findings}
        for marker in ("VIOLATION: if-gated collective",
                       "VIOLATION: guard-return gated",
                       "VIOLATION: world-version gated",
                       "VIOLATION: else-arm gated"):
            assert ("rank-gated-collective",
                    _line_of(lines, marker)) in got, marker
        assert len(rep.findings) == 4

    def test_nondeterministic_submission_order(self):
        rep, lines = _check_fixture("bad_unordered.py")
        got = {(f.check, f.line) for f in rep.findings}
        for marker in ("VIOLATION: set iteration",
                       "VIOLATION: listdir iteration",
                       "VIOLATION: set attribute iteration"):
            assert ("nondeterministic-submission-order",
                    _line_of(lines, marker)) in got, marker
        # sorted(os.listdir(...)) is deterministic — not a finding
        assert len(rep.findings) == 3

    def test_unagreed_selection_input(self):
        rep, lines = _check_fixture("bad_unagreed.py")
        got = {(f.check, f.line) for f in rep.findings}
        for marker in ("VIOLATION: env into selection",
                       "VIOLATION: tainted name into sink",
                       "VIOLATION: time into sink"):
            assert ("unagreed-selection-input",
                    _line_of(lines, marker)) in got, marker
        assert len(rep.findings) == 3
        # the agreed-annotated read is enumerated, not flagged
        assert [(a.what, a.how) for a in rep.agreed] == \
            [("value", "launcher exports one env to every rank before spawn")]

    def test_capture_impure_read(self):
        rep, lines = _check_fixture("bad_impure.py")
        got = {(f.check, f.line) for f in rep.findings}
        assert ("capture-impure-read",
                _line_of(lines, "VIOLATION: env read on step path")) in got
        assert ("capture-impure-read",
                _line_of(lines, "VIOLATION: host I/O on step path")) in got
        # __init__ knob resolution and the off-path read are exempt
        assert len(rep.findings) == 2

    def test_suppression_hygiene(self):
        rep, lines = _check_fixture("bad_suppression.py")
        checks = {f.check: f.line for f in rep.findings}
        assert checks["bad-suppression"] == \
            _line_of(lines, "divcheck: ignore", 0)
        assert checks["stale-suppression"] == \
            _line_of(lines, "old excuse for code that changed")
        assert checks["bad-annotation"] == \
            _line_of(lines, "divcheck: agreed[]")
        assert checks["stale-agreed"] == \
            _line_of(lines, "nothing here is rank-local")
        assert rep.suppressions == []
        assert rep.agreed == []

    def test_cross_file_call_graph(self):
        rep, _ = _check_fixture("xfile")
        f, = rep.findings
        assert f.check == "rank-gated-collective"
        assert f.file.endswith("gated.py")
        lines = open(os.path.join(FIXTURES, "xfile",
                                  "gated.py")).read().splitlines()
        assert f.line == _line_of(lines, "VIOLATION: cross-file rank gate")
        assert "sync_gradients" in f.message

    def test_clean_fixture_zero_findings(self):
        rep, _ = _check_fixture("clean.py")
        assert rep.findings == []
        assert rep.suppressions == []
        assert len(rep.agreed) == 1  # the agreed condition is enumerated


class TestConventions:
    def test_guard_return_gates_rest_of_block(self):
        rep = divcheck.check_source(
            "import horovod_tpu as hvd\n"
            "def f(g, rank):\n"
            "    if rank != 0:\n"
            "        return g\n"
            "    return hvd.allreduce(g)\n")
        assert [(f.check, f.line) for f in rep.findings] == \
            [("rank-gated-collective", 5)]

    def test_size_gate_is_not_rank_local(self):
        rep = divcheck.check_source(
            "import horovod_tpu as hvd\n"
            "def f(eng, g):\n"
            "    if eng.backend.size() == 1:\n"
            "        return g\n"
            "    return hvd.allreduce(g)\n")
        assert rep.findings == []

    def test_agreed_condition_standalone_above(self):
        rep = divcheck.check_source(
            "import horovod_tpu as hvd\n"
            "def f(g, rank):\n"
            "    # divcheck: agreed[rank 0 broadcast decided this upstream]\n"
            "    if rank == 0:\n"
            "        return hvd.allreduce(g)\n"
            "    return g\n")
        assert rep.findings == []
        assert [(a.line, a.what) for a in rep.agreed] == [(3, "condition")]

    def test_agreed_order_on_for_loop(self):
        rep = divcheck.check_source(
            "import horovod_tpu as hvd\n"
            "def f(names):\n"
            "    out = []\n"
            "    for n in set(names):  # divcheck: agreed[one name only ever lands here]\n"
            "        out.append(hvd.allreduce(n))\n"
            "    return out\n")
        assert rep.findings == []
        assert [a.what for a in rep.agreed] == ["order"]

    def test_init_phase_exemption(self):
        rep = divcheck.check_source(
            "import os\n"
            "class E:\n"
            "    def __init__(self):\n"
            "        self.t = os.environ.get('K')\n"
            "    def allreduce(self, x):\n"
            "        return x\n")
        assert rep.findings == []

    def test_env_helper_defs_are_exempt_callers_are_not(self):
        rep = divcheck.check_source(
            "import os\n"
            "def _get_int(name, default):\n"
            "    return int(os.environ.get(name, default))\n"
            "def allreduce(x):\n"
            "    return x * _get_int('K', 1)\n")
        assert [(f.check, f.line, f.func) for f in rep.findings] == \
            [("capture-impure-read", 5, "allreduce")]

    def test_reasoned_suppression_is_counted(self):
        rep = divcheck.check_source(
            "import horovod_tpu as hvd\n"
            "def f(g, rank):\n"
            "    if rank == 0:\n"
            "        return hvd.allreduce(g)  # divcheck: ignore[single-rank tool path, never runs inside a job]\n"
            "    return g\n")
        assert rep.findings == []
        assert [(s.check, s.reason) for s in rep.suppressions] == \
            [("rank-gated-collective",
              "single-rank tool path, never runs inside a job")]

    def test_trailing_suppression_does_not_bleed(self):
        rep = divcheck.check_source(
            "import horovod_tpu as hvd\n"
            "def f(g, rank):\n"
            "    if rank == 0:\n"
            "        hvd.allreduce(g)  # divcheck: ignore[excused line]\n"
            "        hvd.broadcast(g, 0)\n"
            "    return g\n")
        assert [(f.check, f.line) for f in rep.findings] == \
            [("rank-gated-collective", 5)]
        assert len(rep.suppressions) == 1

    def test_common_names_do_not_propagate(self):
        # a def named get() that allreduces must not make every dict.get
        # call in the tree collective-issuing
        rep = divcheck.check_sources({
            "a.py": ("import horovod_tpu as hvd\n"
                     "class C:\n"
                     "    def get(self):\n"
                     "        return hvd.allreduce(1)\n"),
            "b.py": ("def f(d, rank):\n"
                     "    if rank == 0:\n"
                     "        return d.get('k')\n"
                     "    return None\n")})
        assert rep.findings == []

    def test_self_call_resolution_beats_name_collision(self):
        # Registry._get calls self._validate — its OWN _validate, not the
        # estimator's collective-issuing one
        rep = divcheck.check_sources({
            "a.py": ("import horovod_tpu as hvd\n"
                     "class Estimator:\n"
                     "    def _probe(self):\n"
                     "        return hvd.allreduce(1)\n"),
            "b.py": ("class Registry:\n"
                     "    def _probe(self):\n"
                     "        return 1\n"
                     "    def lookup(self, rank):\n"
                     "        if rank == 0:\n"
                     "            return self._probe()\n"
                     "        return None\n")})
        assert rep.findings == []

    def test_world_version_subscript_compare(self):
        rep = divcheck.check_source(
            "import horovod_tpu as hvd\n"
            "def f(hdr, cached, g):\n"
            "    if hdr['world_version'] != cached:\n"
            "        hvd.barrier()\n"
            "    return g\n")
        assert [(f.check, f.line) for f in rep.findings] == \
            [("rank-gated-collective", 4)]

    def test_unparseable_file_is_a_finding_not_a_crash(self):
        rep = divcheck.check_source("def broken(:\n  '''unterminated\n")
        assert [f.check for f in rep.findings] == ["parse-error"]


class TestLiveTree:
    def test_horovod_tpu_is_divergence_clean(self):
        rep = divcheck.check_package(PKG_ROOT)
        assert rep.findings == [], "\n".join(str(f) for f in rep.findings)

    def test_every_live_suppression_carries_a_reason(self):
        rep = divcheck.check_package(PKG_ROOT)
        assert rep.suppressions, "the annotated tree should have suppressions"
        for s in rep.suppressions:
            assert s.reason and s.reason.strip(), str(s)

    def test_every_live_agreed_site_documents_the_exchange(self):
        rep = divcheck.check_package(PKG_ROOT)
        assert rep.agreed, "the annotated tree should have agreed sites"
        for a in rep.agreed:
            assert a.how and a.how.strip(), f"{a.file}:{a.line}"

    def test_scan_coverage_is_not_vacuous(self):
        # a gutted call graph would zero these out long before any
        # finding regressed — pin the floor
        rep = divcheck.check_package(PKG_ROOT)
        assert rep.files >= 60
        assert rep.defs >= 700
        assert rep.issuing_defs >= 80
        assert rep.step_path_defs >= 100
