"""Topology layer + collective algorithm selection (ISSUE 10).

Unit surface: the :class:`~horovod_tpu.parallel.mesh.Topology`
descriptor (detection, the HOROVOD_TPU_LOCAL_SIZE override,
non-divisible fallback), the pure selection rules
(``ops.collectives.choose_algorithm`` / ``validate_algorithm``), the
per-link wire attribution (``link_split`` + the engine's link-labeled
accounting), the trace/report link breakdown, and the bench sweep's
perf smoke. Compiled-program structure per selected algorithm lives in
tests/test_compiled_structure.py; real np=2 forced-algorithm parity in
tests/test_multiprocess.py.
"""

import logging

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from horovod_tpu.common.env import HOROVOD_TPU_LOCAL_SIZE
from horovod_tpu.ops import collectives as C
from horovod_tpu.parallel.mesh import Topology, detect_topology


def _topo(size, local, platform="tpu"):
    return Topology(size=size, local_size=local, platform=platform,
                    source="override")


# ---------------------------------------------------------------------------
# Topology descriptor + detection
# ---------------------------------------------------------------------------

class TestTopology:
    def test_hierarchical_ok_requires_nontrivial_exact_factorization(self):
        assert _topo(8, 4).hierarchical_ok
        assert not _topo(8, 1).hierarchical_ok   # flat
        assert not _topo(8, 8).hierarchical_ok   # one island
        assert not _topo(6, 4).hierarchical_ok   # non-divisible
        assert not _topo(1, 1).hierarchical_ok

    def test_groups_are_contiguous_slice_major(self):
        t = _topo(8, 4)
        assert t.local_groups() == [[0, 1, 2, 3], [4, 5, 6, 7]]
        assert t.cross_groups() == [[0, 4], [1, 5], [2, 6], [3, 7]]
        assert t.num_slices == 2 and t.is_multislice

    def test_roofline_shapes(self):
        t = _topo(8, 4)
        flat = t.roofline_busbw_gbps("allreduce", "flat")
        hier = t.roofline_busbw_gbps("allreduce", "hierarchical")
        tree = t.roofline_busbw_gbps("allreduce", "tree")
        # multislice flat ring is paced by DCN; the hierarchical ladder
        # recovers up to local_size x of it (capped by ICI); tree divides
        # by log2(n)
        assert flat == t.dcn_gbps
        assert hier == min(t.ici_gbps, t.dcn_gbps * 4)
        assert hier > flat
        # hierarchical ALLGATHER is DCN-paced (whole slice blocks cross;
        # the win is hop count, not bandwidth) — no local_size recovery
        assert t.roofline_busbw_gbps("allgather", "hierarchical") \
            == min(t.ici_gbps, t.dcn_gbps)
        # tree rounds each move the full payload: base fabric / log2(n)
        assert tree == pytest.approx(t.dcn_gbps / 3)
        single = _topo(8, 1)
        assert single.roofline_busbw_gbps("allreduce", "flat") \
            == single.ici_gbps
        assert single.roofline_busbw_gbps("allreduce", "tree") \
            == pytest.approx(single.ici_gbps / 3)

    def test_detect_env_override_wins(self, monkeypatch):
        monkeypatch.setenv(HOROVOD_TPU_LOCAL_SIZE, "4")
        t = detect_topology(size=8, local_size=2)
        assert t.local_size == 4 and t.source == "override"
        assert t.hierarchical_ok

    def test_detect_launcher_local_size(self, monkeypatch):
        monkeypatch.delenv(HOROVOD_TPU_LOCAL_SIZE, raising=False)
        t = detect_topology(size=8, local_size=2)
        assert t.local_size == 2 and t.source == "process"

    def test_detect_nondivisible_falls_back_to_divisor(self, monkeypatch,
                                                       caplog):
        monkeypatch.setenv(HOROVOD_TPU_LOCAL_SIZE, "4")
        with caplog.at_level(logging.WARNING, logger="horovod_tpu"):
            t = detect_topology(size=6)
        # largest divisor of 6 that is <= 4
        assert t.local_size == 3
        assert t.hierarchical_ok
        assert any("does not divide" in r.message for r in caplog.records)

    def test_detect_from_devices_flat_cpu_world(self, monkeypatch):
        monkeypatch.delenv(HOROVOD_TPU_LOCAL_SIZE, raising=False)
        # the 8 forced-CPU devices share one process: one island -> flat
        t = detect_topology(devices=jax.devices())
        assert t.size == len(jax.devices())
        assert t.local_size == 1 and t.source == "flat"
        assert t.platform == "cpu"

    def test_detect_slice_attrs(self, monkeypatch):
        monkeypatch.delenv(HOROVOD_TPU_LOCAL_SIZE, raising=False)

        class FakeDev:
            platform = "tpu"

            def __init__(self, slice_index, process_index):
                self.slice_index = slice_index
                self.process_index = process_index

        devs = [FakeDev(i // 4, 0) for i in range(8)]
        t = detect_topology(devices=devs)
        assert t.local_size == 4 and t.source == "slice_attrs"
        assert t.platform == "tpu" and t.hierarchical_ok


# ---------------------------------------------------------------------------
# selection rules
# ---------------------------------------------------------------------------

class TestChooseAlgorithm:
    def test_auto_small_reduction_is_tree(self):
        t = _topo(8, 4)
        assert C.choose_algorithm("allreduce", 64 * 1024, t) == "tree"

    def test_auto_large_reduction_is_hierarchical_on_multislice(self):
        t = _topo(8, 4)
        assert C.choose_algorithm("allreduce", 8 * 1024 ** 2, t) \
            == "hierarchical"
        assert C.choose_algorithm("allgather", 8 * 1024 ** 2, t) \
            == "hierarchical"

    def test_auto_large_reduction_is_flat_on_single_slice(self):
        t = _topo(8, 1)
        assert C.choose_algorithm("allreduce", 8 * 1024 ** 2, t) == "flat"

    def test_auto_never_trees_tiny_worlds_or_non_pow2(self):
        assert C.choose_algorithm("allreduce", 1024, _topo(2, 1)) == "flat"
        assert C.choose_algorithm("allreduce", 1024, _topo(6, 1)) == "flat"

    def test_reducescatter_is_always_flat(self):
        t = _topo(8, 4)
        assert C.choose_algorithm("reducescatter", 8 * 1024 ** 2, t) \
            == "flat"
        assert C.validate_algorithm("reducescatter", "hierarchical", 8, 4) \
            == "flat"

    def test_forced_invalid_demotes_never_raises(self):
        # tree on a non-power-of-2 world
        assert C.choose_algorithm("allreduce", 10, _topo(6, 1),
                                  force="tree") == "flat"
        # hierarchical with no exact factorization (the old assert site)
        assert C.choose_algorithm("allreduce", 10, _topo(6, 4),
                                  force="hierarchical") == "flat"
        # unknown name
        assert C.validate_algorithm("allreduce", "quantum", 8, 4) == "flat"

    def test_forced_valid_sticks_at_any_size(self):
        t = _topo(8, 4)
        assert C.choose_algorithm("allreduce", 8 * 1024 ** 2, t,
                                  force="tree") == "tree"
        assert C.choose_algorithm("allreduce", 16, t,
                                  force="hierarchical") == "hierarchical"
        assert C.choose_algorithm("allreduce", 16, t, force="flat") == "flat"

    def test_tree_threshold_knob_moves_the_boundary(self):
        t = _topo(8, 1)
        assert C.choose_algorithm("allreduce", 1024, t,
                                  tree_threshold_bytes=512) == "flat"
        assert C.choose_algorithm("allreduce", 1024, t,
                                  tree_threshold_bytes=2048) == "tree"

    def test_size_one_world_is_flat(self):
        assert C.choose_algorithm("allreduce", 1024, _topo(1, 1)) == "flat"

    def test_tree_groups_structure(self):
        rounds = C.tree_groups(8)
        assert rounds[0] == [[0, 1], [2, 3], [4, 5], [6, 7]]
        assert rounds[1] == [[0, 2], [1, 3], [4, 6], [5, 7]]
        assert rounds[2] == [[0, 4], [1, 5], [2, 6], [3, 7]]


# ---------------------------------------------------------------------------
# per-link wire attribution
# ---------------------------------------------------------------------------

class TestLinkSplit:
    def test_flat_and_tree_ride_link_flat(self):
        assert C.link_split("flat", 1000, 4) == {"flat": 1000}
        assert C.link_split("tree", 1000, 4) == {"flat": 1000}

    def test_hierarchical_splits_preserving_totals(self):
        split = C.link_split("hierarchical", 1000, 4)
        assert split["dcn"] == 250           # the 1/local_size cross leg
        assert split["ici"] == 750
        assert sum(split.values()) == 1000

    def test_hierarchical_allgather_attributes_payload_to_dcn(self):
        # the cross gather moves whole slice blocks: every byte crosses
        # DCN — no 1/local_size reduction to claim (that is allreduce's)
        assert C.link_split("hierarchical", 1000, 4, kind="allgather") \
            == {"dcn": 1000}

    def test_engine_wire_counter_carries_link_labels(self):
        """The acceptance surface: the metrics snapshot shows the ici/dcn
        wire split when a hierarchical bucket is accounted."""
        import horovod_tpu as hvd
        from horovod_tpu import metrics as hvd_metrics
        hvd.init()
        eng = hvd._engine()
        x = jnp.ones((256,), jnp.float32)  # 1024 bytes
        links = [C.link_split("hierarchical", x.nbytes, 4)]
        base = hvd_metrics.snapshot()
        eng._m_account("grouped_allreduce", [x], links)
        snap = hvd_metrics.snapshot()

        def val(s, **labels):
            want = tuple(sorted(labels.items()))
            for l, v in s["counters"].get("hvd_tpu_wire_bytes_total",
                                          {"values": []})["values"]:
                if tuple(sorted(l.items())) == want:
                    return v
            return 0.0

        labels = dict(kind="grouped_allreduce", dtype="float32")
        assert val(snap, link="ici", **labels) \
            - val(base, link="ici", **labels) == 768.0
        assert val(snap, link="dcn", **labels) \
            - val(base, link="dcn", **labels) == 256.0

    def test_engine_selection_counter_and_flat_link_on_size1(self):
        """A size-1 world moves every byte over link="flat" and never
        splits (selection inactive)."""
        import horovod_tpu as hvd
        from horovod_tpu import metrics as hvd_metrics
        hvd.init()
        base = hvd_metrics.snapshot()
        hvd.allreduce(np.ones(16, np.float32), name="topo.ar", op=hvd.Sum)
        snap = hvd_metrics.snapshot()
        rows = {tuple(sorted(l.items()))
                for l, _ in snap["counters"]["hvd_tpu_wire_bytes_total"]
                ["values"]}
        assert (("dtype", "float32"), ("kind", "allreduce"),
                ("link", "flat")) in rows


# ---------------------------------------------------------------------------
# trace + report link breakdown
# ---------------------------------------------------------------------------

class TestTraceLinkBreakdown:
    def test_link_bytes_rides_the_merged_trace_and_report(self):
        from horovod_tpu.trace import TraceRecorder, merge_segments
        import tools.trace_report as tr
        recs = {}
        for r in range(2):
            rec = TraceRecorder(rank=r)
            rec.record_enqueue("grad.0", "grouped_allreduce", 1000, 0,
                              link_bytes={"ici": 750, "dcn": 250})
            rec.record_done("grad.0")
            rec.record_enqueue("b.0", "broadcast", 64, 0)
            rec.record_done("b.0")
            recs[r] = rec.segment()
        events = merge_segments(recs)
        # schema lint stays green with the new args key
        assert tr.check_events(events) == []
        links = tr.wire_by_link(events)
        assert links["GROUPED_ALLREDUCE"] == {"ici": 1500, "dcn": 500}
        assert "BROADCAST" not in links  # no stamp -> no row
        rep = tr.analyze(events)
        assert rep["wire_by_link"]["GROUPED_ALLREDUCE"]["dcn"] == 500
        assert rep["skew_by_kind"]["GROUPED_ALLREDUCE"][
            "wire_bytes_by_link"] == {"ici": 1500, "dcn": 500}


# ---------------------------------------------------------------------------
# bench sweep smoke (tier-1-safe, perf marker)
# ---------------------------------------------------------------------------

@pytest.mark.perf
def test_perf_smoke_busbw_sweep_one_band():
    """Build + run the bus-bandwidth sweep for one small band on the CPU
    world — no timing assertions, just that the sweep emits the
    busbw/roofline/selected-algorithm fields the acceptance names."""
    from bench import bench_busbw
    r = bench_busbw(sizes_bytes=[64 * 1024], iters=1)
    assert "busbw_allreduce_64KB" in r and r["busbw_allreduce_64KB"] > 0
    assert r["busbw_roofline_allreduce_64KB"] > 0
    assert r["collective_algo_selected"]["allreduce_64KB"] in C.ALGORITHMS
    assert r["collective_algo_selected"]["allgather_64KB"] in C.ALGORITHMS
    assert r["busbw_topology"]["size"] == 8


@pytest.mark.perf
def test_perf_smoke_alltoall_busbw_one_band():
    """ISSUE 17: the sweep's alltoall kind — (n-1)/n busbw convention,
    measured-vs-roofline pair, and the per-band selected algorithm
    resolved through the alltoall-specific knobs."""
    from bench import bench_busbw
    r = bench_busbw(sizes_bytes=[64 * 1024], iters=1)
    assert "busbw_alltoall_64KB" in r and r["busbw_alltoall_64KB"] > 0
    assert r["busbw_roofline_alltoall_64KB"] > 0
    assert r["collective_algo_selected"]["alltoall_64KB"] in C.ALGORITHMS


# ---------------------------------------------------------------------------
# replay re-arms when selection knobs move
# ---------------------------------------------------------------------------

def test_replay_rearms_on_collective_algo_knob_move():
    """A live move of the algorithm knob (env force or the autotune
    categorical) must rebuild armed replay programs — eager warmup and
    the armed program always resolve the same schedule."""
    import horovod_tpu as hvd
    hvd.init()
    eng = hvd._engine()
    prev = (eng.config.step_replay_warmup, eng.config.collective_algo)
    eng.config.step_replay_warmup = 2
    eng.replay.invalidate_all("test isolation")
    tensors = [jnp.ones((8,), jnp.float32) for _ in range(3)]
    try:
        for i in range(3):
            eng.step_begin()
            hvd.grouped_allreduce(list(tensors), name=f"ra.{i}", op=hvd.Sum)
            eng.step_end()
        assert eng.replay.replayed_steps >= 1
        armed = [e["armed"] for e in eng.replay._seen.values()
                 if e.get("armed")]
        assert armed and armed[0].algo_sig[0] == "auto"
        eng.config.collective_algo = "flat"
        eng.step_begin()
        hvd.grouped_allreduce(list(tensors), name="ra.3", op=hvd.Sum)
        eng.step_end()
        rearmed = [e["armed"] for e in eng.replay._seen.values()
                   if e.get("armed")]
        assert rearmed and rearmed[0].algo_sig[0] == "flat"
    finally:
        (eng.config.step_replay_warmup, eng.config.collective_algo) = prev
        eng.replay.invalidate_all("test isolation")
