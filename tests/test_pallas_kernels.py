"""Pallas kernel correctness vs the lax reference implementations (interpret
mode on the CPU world; the same code compiles via Mosaic on real TPU — see
bench_kernels.py for the measured numbers that set the defaults)."""

import numpy as np
import pytest
import jax.numpy as jnp

from horovod_tpu.ops.adasum import adasum_combine
from horovod_tpu.ops.pallas_kernels import (adasum_combine_pallas,
                                            pack_pallas, pallas_supported)

pytestmark = pytest.mark.skipif(not pallas_supported(),
                                reason="pallas unavailable")


@pytest.mark.parametrize("shape,dtype", [
    ((1000,), np.float32),
    ((70000,), np.float32),
    ((3, 5, 7), np.float32),
    ((65536,), "bfloat16"),
])
def test_adasum_combine_matches_lax(shape, dtype):
    rng = np.random.RandomState(0)
    a = jnp.asarray(rng.randn(*shape), dtype)
    b = jnp.asarray(rng.randn(*shape), dtype)
    got = np.asarray(adasum_combine_pallas(a, b), np.float32)
    want = np.asarray(adasum_combine(a, b), np.float32)
    np.testing.assert_allclose(got, want, rtol=2e-2 if dtype == "bfloat16"
                               else 2e-5, atol=1e-5)


def test_adasum_combine_zero_operand():
    a = jnp.zeros((512,), jnp.float32)
    b = jnp.asarray(np.random.RandomState(1).randn(512), jnp.float32)
    got = np.asarray(adasum_combine_pallas(a, b))
    want = np.asarray(adasum_combine(a, b))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_env_knob_switches_impl(monkeypatch):
    monkeypatch.setenv("HOROVOD_ADASUM_PALLAS", "1")
    a = jnp.asarray(np.random.RandomState(2).randn(256), jnp.float32)
    out = np.asarray(adasum_combine(a, a))
    np.testing.assert_allclose(out, np.asarray(a), rtol=1e-5)


def test_pack_pallas_matches_concat():
    rng = np.random.RandomState(3)
    ts = [jnp.asarray(rng.randn(*s), jnp.float32)
          for s in [(5,), (3, 4), (2, 2, 2), (1,)]]
    got = np.asarray(pack_pallas(ts))
    want = np.concatenate([np.asarray(t).ravel() for t in ts])
    np.testing.assert_array_equal(got, want)


# -- fused BatchNorm kernels + module (docs/roofline.md) --------------------


@pytest.mark.parametrize("m,c", [(1000, 256), (1000, 64), (512, 128),
                                 (777, 384)])
def test_bn_stats_matches_numpy(m, c):
    from horovod_tpu.ops.pallas_kernels import bn_stats_pallas
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(m, c), "bfloat16")
    s, q = bn_stats_pallas(x)
    xf = np.asarray(x, np.float32)
    np.testing.assert_allclose(np.asarray(s), xf.sum(0), rtol=2e-2, atol=1e-2)
    np.testing.assert_allclose(np.asarray(q), (xf * xf).sum(0), rtol=2e-2,
                               atol=1e-2)


def test_bn_bwd_stats_matches_numpy():
    from horovod_tpu.ops.pallas_kernels import bn_bwd_stats_pallas
    rng = np.random.RandomState(1)
    m, c = 900, 256
    x = jnp.asarray(rng.randn(m, c), "bfloat16")
    dy = jnp.asarray(rng.randn(m, c), "bfloat16")
    xf, dyf = np.asarray(x, np.float32), np.asarray(dy, np.float32)
    mean = jnp.asarray(xf.mean(0))
    invstd = jnp.asarray(1.0 / (xf.std(0) + 1e-5))
    s1, s2 = bn_bwd_stats_pallas(dy, x, mean, invstd)
    xh = (xf - np.asarray(mean)) * np.asarray(invstd)
    np.testing.assert_allclose(np.asarray(s1), dyf.sum(0), rtol=2e-2,
                               atol=1e-1)
    np.testing.assert_allclose(np.asarray(s2), (dyf * xh).sum(0), rtol=3e-2,
                               atol=2e-1)


def test_fused_batch_norm_matches_flax():
    """FusedBatchNorm must match nn.BatchNorm: outputs, all three gradients,
    running-stat EMA, and eval mode (fp32 so the comparison is tight)."""
    import jax
    import flax.linen as nn
    from horovod_tpu.ops.fused_batch_norm import FusedBatchNorm

    x = jnp.asarray(np.random.RandomState(0).randn(8, 5, 5, 12), jnp.float32)
    ref = nn.BatchNorm(use_running_average=False, momentum=0.9, epsilon=1e-5,
                       dtype=jnp.float32, param_dtype=jnp.float32)
    fus = FusedBatchNorm(use_running_average=False, momentum=0.9,
                         epsilon=1e-5, dtype=jnp.float32)
    vr = ref.init(jax.random.PRNGKey(0), x)
    vf = fus.init(jax.random.PRNGKey(0), x)

    def run(mod, p, bs, x):
        y, mut = mod.apply({"params": p, "batch_stats": bs}, x,
                           mutable=["batch_stats"])
        return y, mut["batch_stats"]

    yr, bsr = run(ref, vr["params"], vr["batch_stats"], x)
    yf, bsf = run(fus, vr["params"], vf["batch_stats"], x)
    np.testing.assert_allclose(np.asarray(yr), np.asarray(yf), atol=1e-5)
    np.testing.assert_allclose(np.asarray(bsr["mean"]),
                               np.asarray(bsf["mean"]), atol=1e-6)
    np.testing.assert_allclose(np.asarray(bsr["var"]),
                               np.asarray(bsf["var"]), atol=1e-5)

    def loss(mod, v0, p, x):
        return jnp.sum(jnp.sin(run(mod, p, v0["batch_stats"], x)[0]))

    gr = jax.grad(lambda p: loss(ref, vr, p, x))(vr["params"])
    gf = jax.grad(lambda p: loss(fus, vf, p, x))(vr["params"])
    np.testing.assert_allclose(np.asarray(gr["scale"]),
                               np.asarray(gf["scale"]), atol=1e-4)
    np.testing.assert_allclose(np.asarray(gr["bias"]),
                               np.asarray(gf["bias"]), atol=1e-4)
    gxr = jax.grad(lambda x: loss(ref, vr, vr["params"], x))(x)
    gxf = jax.grad(lambda x: loss(fus, vf, vr["params"], x))(x)
    np.testing.assert_allclose(np.asarray(gxr), np.asarray(gxf), atol=1e-4)

    refe = nn.BatchNorm(use_running_average=True, momentum=0.9, epsilon=1e-5,
                        dtype=jnp.float32, param_dtype=jnp.float32)
    fuse = FusedBatchNorm(use_running_average=True, momentum=0.9,
                          epsilon=1e-5, dtype=jnp.float32)
    ye = refe.apply({"params": vr["params"], "batch_stats": bsr}, x)
    yfe = fuse.apply({"params": vr["params"], "batch_stats": bsf}, x)
    np.testing.assert_allclose(np.asarray(ye), np.asarray(yfe), atol=1e-5)


def test_resnet_fused_bn_variant_trains():
    """ResNet(fused_bn=True) runs fwd+bwd on the CPU world (XLA fallback of
    the same custom_vjp path the TPU kernels use)."""
    import jax
    import optax
    from horovod_tpu.models.resnet import ResNet18ish

    m = ResNet18ish(num_classes=10, dtype=jnp.float32, fused_bn=True)
    x = jnp.asarray(np.random.RandomState(0).rand(2, 32, 32, 3), jnp.float32)
    v = m.init(jax.random.PRNGKey(0), x, train=True)

    def loss(p):
        logits, _ = m.apply({"params": p, "batch_stats": v["batch_stats"]},
                            x, train=True, mutable=["batch_stats"])
        return jnp.mean(logits ** 2)

    g = jax.grad(loss)(v["params"])
    assert all(np.isfinite(np.asarray(leaf)).all()
               for leaf in jax.tree_util.tree_leaves(g))


class TestSplashRematSelection:
    """VERDICT r4 item 7: splash must auto-degrade to flash when a remat'd
    block would recompute its residual-saving forward with a VMEM
    residency above the chip scope — the env knobs are overrides, not the
    mechanism. The selection arithmetic is backend-independent."""

    def test_flagship_remat_shape_degrades_to_flash(self, monkeypatch):
        from horovod_tpu.parallel import flash_attention as fa
        monkeypatch.delenv("HOROVOD_SPLASH", raising=False)
        monkeypatch.delenv("HOROVOD_SPLASH_BLOCK_KV", raising=False)
        # T=2048 D=128 (flagship): bkv=2048 recompute bound > 16 MiB scope
        assert fa._splash_remat_vmem_bytes(2048, 128, 2048) > \
            fa._scoped_vmem_bytes()
        assert fa._select_kernel(2048, 128, under_remat=True) == "flash"
        # ...but without remat splash stays
        assert fa._select_kernel(2048, 128, under_remat=False) == "splash"

    def test_small_block_fits_and_keeps_splash(self, monkeypatch):
        from horovod_tpu.parallel import flash_attention as fa
        # the other empirical anchor: bkv=1024 fits under the scope
        assert fa._splash_remat_vmem_bytes(2048, 128, 1024) < \
            fa._scoped_vmem_bytes()
        monkeypatch.setenv("HOROVOD_SPLASH_BLOCK_KV", "1024")
        assert fa._select_kernel(2048, 128, under_remat=True) == "splash"

    def test_force_overrides_degrade(self, monkeypatch):
        from horovod_tpu.parallel import flash_attention as fa
        monkeypatch.setenv("HOROVOD_SPLASH", "force")
        monkeypatch.delenv("HOROVOD_SPLASH_BLOCK_KV", raising=False)
        assert fa._select_kernel(2048, 128, under_remat=True) == "splash"
