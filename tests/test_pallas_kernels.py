"""Pallas kernel correctness vs the lax reference implementations (interpret
mode on the CPU world; the same code compiles via Mosaic on real TPU — see
bench_kernels.py for the measured numbers that set the defaults)."""

import numpy as np
import pytest
import jax.numpy as jnp

from horovod_tpu.ops.adasum import adasum_combine
from horovod_tpu.ops.pallas_kernels import (adasum_combine_pallas,
                                            pack_pallas, pallas_supported)

pytestmark = pytest.mark.skipif(not pallas_supported(),
                                reason="pallas unavailable")


@pytest.mark.parametrize("shape,dtype", [
    ((1000,), np.float32),
    ((70000,), np.float32),
    ((3, 5, 7), np.float32),
    ((65536,), "bfloat16"),
])
def test_adasum_combine_matches_lax(shape, dtype):
    rng = np.random.RandomState(0)
    a = jnp.asarray(rng.randn(*shape), dtype)
    b = jnp.asarray(rng.randn(*shape), dtype)
    got = np.asarray(adasum_combine_pallas(a, b), np.float32)
    want = np.asarray(adasum_combine(a, b), np.float32)
    np.testing.assert_allclose(got, want, rtol=2e-2 if dtype == "bfloat16"
                               else 2e-5, atol=1e-5)


def test_adasum_combine_zero_operand():
    a = jnp.zeros((512,), jnp.float32)
    b = jnp.asarray(np.random.RandomState(1).randn(512), jnp.float32)
    got = np.asarray(adasum_combine_pallas(a, b))
    want = np.asarray(adasum_combine(a, b))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_env_knob_switches_impl(monkeypatch):
    monkeypatch.setenv("HOROVOD_ADASUM_PALLAS", "1")
    a = jnp.asarray(np.random.RandomState(2).randn(256), jnp.float32)
    out = np.asarray(adasum_combine(a, a))
    np.testing.assert_allclose(out, np.asarray(a), rtol=1e-5)


def test_pack_pallas_matches_concat():
    rng = np.random.RandomState(3)
    ts = [jnp.asarray(rng.randn(*s), jnp.float32)
          for s in [(5,), (3, 4), (2, 2, 2), (1,)]]
    got = np.asarray(pack_pallas(ts))
    want = np.concatenate([np.asarray(t).ravel() for t in ts])
    np.testing.assert_array_equal(got, want)
