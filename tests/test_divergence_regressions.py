"""Runtime regressions for the true violations divcheck found (ISSUE 11)
— the PR 7 bar: each fixed violation keeps a test exercising the exact
divergence the static finding predicted.

Violation: ``Engine`` read ``HOROVOD_PALLAS_PACK`` per grouped-allreduce
call ON THE DISPATCH PATH (capture-impure-read). A mid-run env flip
switched the launch structure between two otherwise-identical steps —
under an armed replay stream, later eager calls would diverge from the
stream the replay was captured from, and across ranks an asymmetric flip
(one worker env touched, another not) would compile different programs.
The fix resolves the knob once at engine init (the sanctioned pattern);
live retuning stays with the broadcast-synced autotune categorical.
"""

import os

import jax.numpy as jnp
import pytest

import horovod_tpu as hvd


@pytest.fixture()
def engine(monkeypatch):
    # the ambient env must not leak into the init-time resolution the
    # test pins (a dev rig exporting HOROVOD_PALLAS_PACK=1 would
    # otherwise fail the `is False` assertions spuriously)
    monkeypatch.delenv("HOROVOD_PALLAS_PACK", raising=False)
    hvd.init()
    eng = hvd._engine()
    prev = eng._pack_pallas_base
    eng._pack_pallas_base = False
    yield eng
    eng._pack_pallas_base = prev
    os.environ.pop("HOROVOD_PALLAS_PACK", None)


def _grouped_dispatches(eng):
    tensors = [jnp.ones((8, 8)) * i for i in range(3)]
    before = eng.dispatch_count
    handles = eng.grouped_allreduce(tensors, name="divreg")
    for h in handles:
        h.synchronize()
    return eng.dispatch_count - before


def test_pack_knob_resolves_at_init_not_per_call(engine):
    # the knob state the engine dispatches with is frozen at init
    assert engine._pack_pallas_base is False
    baseline = _grouped_dispatches(engine)

    # a mid-run env flip must NOT change the dispatch structure: the
    # step that armed a replay stream and the step after the flip must
    # issue identical launch sequences
    os.environ["HOROVOD_PALLAS_PACK"] = "1"
    assert engine._pack_pallas_base is False
    flipped = _grouped_dispatches(engine)
    assert flipped == baseline, (
        "HOROVOD_PALLAS_PACK flipped the launch structure mid-run — the "
        "knob must resolve at engine init (divcheck capture-impure-read)")


def test_fresh_engine_picks_up_the_knob_at_init(engine, monkeypatch):
    # init-time resolution is still a real knob: a NEW engine built under
    # the flipped env sees it (the elastic-reset path builds new engines)
    from horovod_tpu.ops.pallas_kernels import pack_pallas_enabled
    monkeypatch.setenv("HOROVOD_PALLAS_PACK", "1")
    assert pack_pallas_enabled() in (True, False)  # gated on support
    # the live engine, built before the flip, is unchanged
    assert engine._pack_pallas_base is False
