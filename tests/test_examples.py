"""Smoke tests for examples/ — run each example end-to-end (tiny settings) in
a subprocess, CI-style (reference: examples are exercised by the buildkite
pipeline, gen-pipeline.sh:163).
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = os.path.join(REPO, "examples")


def _run(args, timeout=420):
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
    })
    return subprocess.run([sys.executable] + args, env=env, timeout=timeout,
                          capture_output=True, text=True)


def test_mnist_mlp_example():
    r = _run([os.path.join(EXAMPLES, "mnist_mlp.py"), "--epochs", "1",
              "--batch-size", "512"])
    assert r.returncode == 0, r.stderr[-2000:]
    assert "loss=" in r.stdout, r.stdout


# Tier-1 budget (ISSUE 9 satellite): the two ResNet50 benchmark
# examples are big-bench subprocesses (78s + 45s measured) — the slow
# marker's other named category. The examples subsystem keeps mnist,
# transformer_lm x3, scaling, elastic and the tpurun CLI run in tier-1;
# ResNet training itself stays covered in-process
# (test_pallas_kernels.py::test_resnet_fused_bn_variant_trains).
@pytest.mark.slow
def test_resnet_benchmark_example_spmd():
    r = _run([os.path.join(EXAMPLES, "resnet50_synthetic_benchmark.py"),
              "--batch-size", "2", "--num-iters", "2", "--num-warmup", "2"])
    assert r.returncode == 0, r.stderr[-2000:]
    assert "Total img/sec" in r.stdout, r.stdout


@pytest.mark.slow
def test_resnet_benchmark_example_eager():
    r = _run([os.path.join(EXAMPLES, "resnet50_synthetic_benchmark.py"),
              "--mode", "eager", "--batch-size", "2", "--num-iters", "2",
              "--num-warmup", "2", "--fp16-allreduce"])
    assert r.returncode == 0, r.stderr[-2000:]
    assert "Img/sec per worker" in r.stdout, r.stdout


def test_elastic_example_single_process():
    r = _run([os.path.join(EXAMPLES, "elastic_synthetic.py"),
              "--total-batches", "20", "--batch-size", "16"])
    assert r.returncode == 0, r.stderr[-2000:]
    assert "done:" in r.stdout, r.stdout


def test_scaling_benchmark_example():
    r = _run([os.path.join(EXAMPLES, "scaling_benchmark.py"),
              "--sizes", "1,2", "--bytes", "1048576", "--iters", "2",
              "--batch-per-chip", "8"])
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [l for l in r.stdout.splitlines() if l.startswith("{")]
    assert len(lines) == 4, r.stdout
    import json
    recs = [json.loads(l) for l in lines]
    assert {rec["bench"] for rec in recs} == {"allreduce",
                                             "weak_scaling_train"}


@pytest.mark.integration
def test_mnist_under_tpurun_cli():
    """Genuine CLI end-to-end: `tpurun -np 2 python examples/mnist_mlp.py`
    (the reference's keystone `horovodrun -np 2` pattern, SURVEY §4)."""
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "HOROVOD_STALL_CHECK_DISABLE": "1",
    })
    r = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         sys.executable, os.path.join(EXAMPLES, "mnist_mlp.py"),
         "--epochs", "1", "--batch-size", "1024"],
        env=env, timeout=420, capture_output=True, text=True)
    assert r.returncode == 0, (r.stdout[-1000:], r.stderr[-2000:])
    assert "size=2" in r.stdout, r.stdout


def test_api_docs_in_sync(tmp_path):
    """docs/api.md must match what tools/gen_api_docs.py generates (the
    docstring-driven reference the README links)."""
    import shutil
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    committed = os.path.join(repo, "docs", "api.md")
    with open(committed) as f:
        before = f.read()
    # run the generator against a scratch copy of the repo's docs dir
    work = tmp_path / "repo"
    work.mkdir()
    (work / "docs").mkdir()
    # the generator writes relative to its own location's parent/docs
    (work / "tools").mkdir()
    shutil.copy(os.path.join(repo, "tools", "gen_api_docs.py"),
                work / "tools" / "gen_api_docs.py")
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    subprocess.run([sys.executable, str(work / "tools" / "gen_api_docs.py")],
                   check=True, env=env, capture_output=True)
    with open(work / "docs" / "api.md") as f:
        regenerated = f.read()
    assert regenerated == before, \
        "docs/api.md is stale — run python tools/gen_api_docs.py"


def test_transformer_lm_example_spmd():
    r = _run([os.path.join(EXAMPLES, "transformer_lm.py"),
              "--mesh", "data=2", "--d-model", "32", "--n-layers", "1",
              "--n-heads", "4", "--d-ff", "64", "--vocab", "128",
              "--seq", "32", "--batch", "4", "--steps", "2"])
    assert r.returncode == 0, r.stderr[-2000:]
    assert "tokens_per_sec" in r.stdout, r.stdout


def test_transformer_lm_example_eager():
    r = _run([os.path.join(EXAMPLES, "transformer_lm.py"),
              "--mode", "eager", "--d-model", "32", "--n-layers", "1",
              "--n-heads", "4", "--d-ff", "64", "--vocab", "128",
              "--seq", "32", "--batch", "4", "--steps", "2"])
    assert r.returncode == 0, r.stderr[-2000:]
    assert "tokens_per_sec" in r.stdout, r.stdout


def test_transformer_lm_example_pp():
    r = _run([os.path.join(EXAMPLES, "transformer_lm.py"),
              "--mode", "pp", "--stages", "2", "--n-micro", "4",
              "--d-model", "32", "--n-layers", "2",
              "--n-heads", "4", "--d-ff", "64", "--vocab", "128",
              "--seq", "32", "--batch", "4", "--steps", "2"])
    assert r.returncode == 0, r.stderr[-2000:]
    assert "tokens_per_sec" in r.stdout, r.stdout


def test_sparse_embedding_example():
    r = _run([os.path.join(EXAMPLES, "sparse_embedding.py"),
              "--steps", "10", "--vocab", "5000"])
    assert r.returncode == 0, r.stderr[-2000:]
    assert "sparse" in r.stdout and "saved" in r.stdout, r.stdout
