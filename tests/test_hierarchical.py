"""Hierarchical (2-level) allreduce tests.

Reference: NCCLHierarchicalAllreduce (ops/nccl_operations.cc:180-383) — local
reduce-scatter → cross allreduce → local allgather, validated here against
the flat allreduce on an 8-device world factored as (cross=2, local=4) and
(cross=4, local=2).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from horovod_tpu.common.reduce_ops import ReduceOp
from horovod_tpu.ops import collectives as C


def _stacked(mesh, shape, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(8, *shape).astype(np.float32)
    garr = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("world")))
    return x, garr


class TestHierarchicalBuilder:
    @pytest.mark.parametrize("local_size", [2, 4, 8])
    @pytest.mark.parametrize("shape", [(16,), (5,), (3, 7)])
    def test_matches_flat_sum(self, mesh8, local_size, shape):
        x, garr = _stacked(mesh8, shape)
        hier = C.build_hierarchical_allreduce(mesh8, "world", local_size,
                                              ReduceOp.SUM)
        out = np.asarray(hier(garr))
        expected = x.sum(axis=0)
        np.testing.assert_allclose(out, expected, rtol=1e-5)

    def test_matches_flat_average(self, mesh8):
        x, garr = _stacked(mesh8, (12,), seed=1)
        hier = C.build_hierarchical_allreduce(mesh8, "world", 4,
                                              ReduceOp.AVERAGE)
        out = np.asarray(hier(garr))
        np.testing.assert_allclose(
            out, x.mean(axis=0), rtol=1e-5)

    def test_min_fallback(self, mesh8):
        x, garr = _stacked(mesh8, (6,), seed=2)
        hier = C.build_hierarchical_allreduce(mesh8, "world", 2,
                                              ReduceOp.MIN)
        out = np.asarray(hier(garr))
        np.testing.assert_allclose(
            out, x.min(axis=0), rtol=1e-6)

    def test_prescale_postscale(self, mesh8):
        x, garr = _stacked(mesh8, (8,), seed=3)
        hier = C.build_hierarchical_allreduce(mesh8, "world", 4,
                                              ReduceOp.SUM,
                                              prescale_factor=0.5,
                                              postscale_factor=2.0)
        out = np.asarray(hier(garr))
        np.testing.assert_allclose(out, x.sum(axis=0), rtol=1e-5)


class TestHierarchicalPrimitive:
    def test_two_axis_mesh(self, mesh8):
        """hierarchical_allreduce_p over an explicit (cross, local) mesh."""
        devs = np.array(jax.devices()[:8]).reshape(2, 4)
        mesh2 = Mesh(devs, ("cross", "local"))
        rng = np.random.RandomState(4)
        x = rng.rand(2, 4, 10).astype(np.float32)
        garr = jax.device_put(jnp.asarray(x),
                              NamedSharding(mesh2, P("cross", "local")))

        from jax import shard_map

        def body(blk):  # (1, 1, 10)
            v = C.hierarchical_allreduce_p(blk[0, 0], "local", "cross",
                                           ReduceOp.SUM)
            return v[None, None]

        fn = jax.jit(shard_map(body, mesh=mesh2,
                               in_specs=P("cross", "local"),
                               out_specs=P("cross", "local")))
        out = np.asarray(fn(garr))
        expected = x.sum(axis=(0, 1), keepdims=True).repeat(2, 0).repeat(4, 1)
        np.testing.assert_allclose(out, expected, rtol=1e-5)


class TestHierarchicalAllgather:
    """HOROVOD_HIERARCHICAL_ALLGATHER: the two-level gather must equal the
    flat allgather exactly (reference MPIHierarchicalAllgather,
    mpi_operations.cc:178)."""

    @pytest.mark.parametrize("local_size", [2, 4, 8])
    @pytest.mark.parametrize("shape", [(3, 4), (1,)])
    def test_matches_flat(self, mesh8, local_size, shape):
        rng = np.random.RandomState(7)
        x = rng.rand(8, *shape).astype(np.float32)
        garr = jax.device_put(jnp.asarray(x),
                              NamedSharding(mesh8, P("world")))
        hier = C.build_hierarchical_allgather(mesh8, "world", local_size)
        flat = C.build_allgather(mesh8, "world")
        np.testing.assert_array_equal(np.asarray(hier(garr)),
                                      np.asarray(flat(garr)))
        # and equals the straight concatenation in rank order
        np.testing.assert_array_equal(
            np.asarray(hier(garr)), x.reshape(8 * shape[0], *shape[1:]))


class TestHierarchicalAdasum:
    """Hierarchical Adasum: local mean -> cross VHDD (coefficients psum'd
    over the sharded node vector) -> local gather
    (adasum_gpu_operations.cc:157-255). Validated against the NumPy VHDD
    reference applied to the per-node mean vectors."""

    @pytest.mark.parametrize("local_size,shape", [
        (2, (32,)), (2, (7, 3)), (4, (16,)), (4, (5,))])
    def test_matches_node_mean_vhdd(self, mesh8, local_size, shape):
        from horovod_tpu.ops.adasum import build_adasum, adasum_reference
        rng = np.random.RandomState(11)
        x = rng.randn(8, *shape).astype(np.float32)
        garr = jax.device_put(jnp.asarray(x),
                              NamedSharding(mesh8, P("world")))
        fn = build_adasum(mesh8, "world", local_size=local_size)
        out = np.asarray(fn(garr))
        cross = 8 // local_size
        node_means = [x[c * local_size:(c + 1) * local_size].mean(axis=0)
                      for c in range(cross)]
        expected = adasum_reference(node_means).reshape(shape)
        np.testing.assert_allclose(out, expected, rtol=1e-4, atol=1e-5)

    def test_local_size_one_equals_flat(self, mesh8):
        from horovod_tpu.ops.adasum import build_adasum
        rng = np.random.RandomState(13)
        x = rng.randn(8, 12).astype(np.float32)
        garr = jax.device_put(jnp.asarray(x),
                              NamedSharding(mesh8, P("world")))
        flat = build_adasum(mesh8, "world")
        h1 = build_adasum(mesh8, "world", local_size=1)
        np.testing.assert_allclose(np.asarray(h1(garr)),
                                   np.asarray(flat(garr)), rtol=1e-6)

    def test_rejects_non_pow2_cross(self, mesh8):
        from horovod_tpu.ops.adasum import hierarchical_adasum_p
        with pytest.raises(ValueError, match="power-of-2"):
            # 8 / 3 isn't even integral; simulate bad factorization directly
            hierarchical_adasum_p(jnp.zeros((4,)), "world", 3, 9)


class TestNonDivisibleFallback:
    """ISSUE 10 satellite: the old hard ``assert n % local_size == 0`` in
    the hierarchical builders crashed non-divisible worlds (e.g. an
    elastic job degraded from 8 to 6 ranks with local_size=4). Every
    builder now demotes to the flat program with a one-time WARNING and
    keeps producing exact results."""

    def _mesh6(self):
        devs = jax.devices()[:6]
        return Mesh(np.array(devs), ("world",))

    def test_allreduce_np6_local4_demotes_to_flat(self, caplog):
        import logging
        mesh = self._mesh6()
        rng = np.random.RandomState(7)
        x = rng.rand(6, 5).astype(np.float32)
        garr = jax.device_put(jnp.asarray(x),
                              NamedSharding(mesh, P("world")))
        C._warned_demotions.clear()
        with caplog.at_level(logging.WARNING, logger="horovod_tpu"):
            hier = C.build_hierarchical_allreduce(mesh, "world", 4,
                                                  ReduceOp.SUM)
            out = np.asarray(hier(garr))
        np.testing.assert_allclose(out, x.sum(axis=0), rtol=1e-5)
        warnings = [r for r in caplog.records
                    if "using flat" in r.getMessage()]
        assert len(warnings) == 1
        # one-time: a second build emits no further warning
        with caplog.at_level(logging.WARNING, logger="horovod_tpu"):
            before = len(caplog.records)
            C.build_hierarchical_allreduce(mesh, "world", 4, ReduceOp.SUM)
            assert not [r for r in caplog.records[before:]
                        if "using flat" in r.getMessage()]

    def test_allgather_np6_local4_demotes_to_flat(self):
        mesh = self._mesh6()
        rng = np.random.RandomState(8)
        x = rng.rand(6, 2, 3).astype(np.float32)
        garr = jax.device_put(jnp.asarray(x),
                              NamedSharding(mesh, P("world")))
        hier = C.build_hierarchical_allgather(mesh, "world", 4)
        flat = C.build_allgather(mesh, "world")
        np.testing.assert_array_equal(np.asarray(hier(garr)),
                                      np.asarray(flat(garr)))

    def test_fused_reduce_np6_local4_demotes_to_flat(self):
        """The fused-bucket reducer closure (_make_reduce_flat, the third
        old assert site) on the same non-divisible world."""
        mesh = self._mesh6()
        shapes = ((10,), (14,))
        fn = C.build_grouped_allreduce(mesh, "world", ReduceOp.SUM,
                                       shapes, [jnp.float32] * 2, [[0, 1]],
                                       local_size=4)
        rng = np.random.RandomState(9)
        data = rng.rand(6, 24).astype(np.float32)
        garr = jax.device_put(jnp.asarray(data),
                              NamedSharding(mesh, P("world")))
        outs = fn(garr)
        expect = data.sum(axis=0)
        np.testing.assert_allclose(np.asarray(outs[0]), expect[:10],
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(outs[1]), expect[10:],
                                   rtol=1e-5)
