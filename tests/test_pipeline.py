"""Pipeline parallelism: the 4-stage microbatched pipeline must match the
sequential stack exactly (forward and gradients), for homogeneous MLP-block
stages on the 8-device world."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.parallel.pipeline import (merge_microbatches,
                                           pipeline_apply_p,
                                           split_microbatches)

N_STAGES = 4
D = 8


def _mesh():
    return jax.sharding.Mesh(np.array(jax.devices()[:N_STAGES]), ("pipe",))


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _stacked_params(seed=0):
    rng = np.random.RandomState(seed)
    return {"w": jnp.asarray(rng.randn(N_STAGES, D, D).astype(np.float32)
                             * 0.5),
            "b": jnp.asarray(rng.randn(N_STAGES, D).astype(np.float32) * 0.1)}


def _sequential(params, x):
    for s in range(N_STAGES):
        x = _stage_fn({"w": params["w"][s], "b": params["b"][s]}, x)
    return x


def _pipeline_fn(mesh):
    def body(params, micro):
        local = {"w": params["w"][0], "b": params["b"][0]}
        return pipeline_apply_p(_stage_fn, local, micro, "pipe", N_STAGES)

    return jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=({"w": P("pipe"), "b": P("pipe")}, P()),
        out_specs=P(), check_vma=False))


@pytest.mark.parametrize("n_micro", [1, 4, 8])
def test_pipeline_matches_sequential(n_micro):
    mesh = _mesh()
    params = _stacked_params()
    x = jnp.asarray(np.random.RandomState(1).randn(16, D).astype(np.float32))
    ref = np.asarray(_sequential(params, x))
    fn = _pipeline_fn(mesh)
    out = merge_microbatches(fn(
        jax.device_put(params, NamedSharding(mesh, P("pipe"))),
        split_microbatches(x, n_micro)))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-6)


def test_pipeline_gradients_match_sequential():
    mesh = _mesh()
    params = _stacked_params(seed=2)
    x = jnp.asarray(np.random.RandomState(3).randn(8, D).astype(np.float32))

    def loss_seq(params):
        return jnp.sum(_sequential(params, x) ** 2)

    gref = jax.grad(loss_seq)(params)

    fn = _pipeline_fn(mesh)
    sharded = jax.device_put(params, NamedSharding(mesh, P("pipe")))
    micro = split_microbatches(x, 4)

    def loss_pipe(params):
        return jnp.sum(merge_microbatches(fn(params, micro)) ** 2)

    g = jax.grad(loss_pipe)(sharded)
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(g[k]), np.asarray(gref[k]),
                                   rtol=1e-4, atol=1e-5)


def test_split_merge_roundtrip():
    x = jnp.arange(24.0).reshape(12, 2)
    np.testing.assert_array_equal(
        np.asarray(merge_microbatches(split_microbatches(x, 3))),
        np.asarray(x))
    with pytest.raises(ValueError, match="divisible"):
        split_microbatches(x, 5)
