"""Pipeline parallelism: the 4-stage microbatched pipeline must match the
sequential stack exactly (forward and gradients), for homogeneous MLP-block
stages on the 8-device world."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.parallel.pipeline import (merge_microbatches,
                                           pipeline_apply_p,
                                           split_microbatches)

N_STAGES = 4
D = 8


def _mesh():
    return jax.sharding.Mesh(np.array(jax.devices()[:N_STAGES]), ("pipe",))


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _stacked_params(seed=0):
    rng = np.random.RandomState(seed)
    return {"w": jnp.asarray(rng.randn(N_STAGES, D, D).astype(np.float32)
                             * 0.5),
            "b": jnp.asarray(rng.randn(N_STAGES, D).astype(np.float32) * 0.1)}


def _sequential(params, x):
    for s in range(N_STAGES):
        x = _stage_fn({"w": params["w"][s], "b": params["b"][s]}, x)
    return x


def _pipeline_fn(mesh):
    def body(params, micro):
        local = {"w": params["w"][0], "b": params["b"][0]}
        return pipeline_apply_p(_stage_fn, local, micro, "pipe", N_STAGES)

    return jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=({"w": P("pipe"), "b": P("pipe")}, P()),
        out_specs=P(), check_vma=False))


@pytest.mark.parametrize("n_micro", [1, 4, 8])
def test_pipeline_matches_sequential(n_micro):
    mesh = _mesh()
    params = _stacked_params()
    x = jnp.asarray(np.random.RandomState(1).randn(16, D).astype(np.float32))
    ref = np.asarray(_sequential(params, x))
    fn = _pipeline_fn(mesh)
    out = merge_microbatches(fn(
        jax.device_put(params, NamedSharding(mesh, P("pipe"))),
        split_microbatches(x, n_micro)))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-6)


def test_pipeline_gradients_match_sequential():
    mesh = _mesh()
    params = _stacked_params(seed=2)
    x = jnp.asarray(np.random.RandomState(3).randn(8, D).astype(np.float32))

    def loss_seq(params):
        return jnp.sum(_sequential(params, x) ** 2)

    gref = jax.grad(loss_seq)(params)

    fn = _pipeline_fn(mesh)
    sharded = jax.device_put(params, NamedSharding(mesh, P("pipe")))
    micro = split_microbatches(x, 4)

    def loss_pipe(params):
        return jnp.sum(merge_microbatches(fn(params, micro)) ** 2)

    g = jax.grad(loss_pipe)(sharded)
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(g[k]), np.asarray(gref[k]),
                                   rtol=1e-4, atol=1e-5)


def test_split_merge_roundtrip():
    x = jnp.arange(24.0).reshape(12, 2)
    np.testing.assert_array_equal(
        np.asarray(merge_microbatches(split_microbatches(x, 3))),
        np.asarray(x))
    with pytest.raises(ValueError, match="divisible"):
        split_microbatches(x, 5)


V = 12  # vocab for the heterogeneous (embed -> blocks -> head) pipeline


def _embed_fn(p, tok):           # [mb, T] int32 -> [mb, T, D]
    return p["emb"][tok]


def _head_fn(p, x):              # [mb, T, D] -> [mb, T, V]
    return x @ p["out"]


def _tblock_fn(p, x):            # [mb, T, D] -> [mb, T, D]
    return x + jnp.tanh(x @ p["w"] + p["b"])


def _lm_params(seed=5):
    rng = np.random.RandomState(seed)
    return {
        "emb": jnp.asarray(rng.randn(V, D).astype(np.float32) * 0.3),
        "w": jnp.asarray(rng.randn(N_STAGES, D, D).astype(np.float32) * 0.4),
        "b": jnp.asarray(rng.randn(N_STAGES, D).astype(np.float32) * 0.1),
        "out": jnp.asarray(rng.randn(D, V).astype(np.float32) * 0.3),
    }


def _lm_sequential(params, tok):
    x = _embed_fn({"emb": params["emb"]}, tok)
    for s in range(N_STAGES):
        x = _tblock_fn({"w": params["w"][s], "b": params["b"][s]}, x)
    return _head_fn({"out": params["out"]}, x)


def _lm_loss(logits, tgt):
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, tgt[..., None], axis=-1))


def _lm_pipeline_fn(mesh, n_micro, remat=False):
    def body(params, micro_tok):
        local = {"w": params["w"][0], "b": params["b"][0]}
        return pipeline_apply_p(
            _tblock_fn, local, micro_tok, "pipe", N_STAGES,
            first_fn=_embed_fn, first_params={"emb": params["emb"]},
            last_fn=_head_fn, last_params={"out": params["out"]},
            remat=remat)

    return jax.shard_map(
        body, mesh=mesh,
        in_specs=({"emb": P(), "w": P("pipe"), "b": P("pipe"),
                   "out": P()}, P()),
        out_specs=P(), check_vma=False)


@pytest.mark.parametrize("remat", [False, True])
def test_pipeline_heterogeneous_lm_matches_dp(remat):
    """VERDICT r3 item 5: a real LM pipeline — embedding (first stage only)
    -> shape-uniform blocks -> head (last stage only) — must produce the
    same loss AND gradients as the unpipelined (DP-style single-replica)
    model, with and without per-stage remat."""
    mesh = _mesh()
    params = _lm_params()
    rng = np.random.RandomState(6)
    tok = jnp.asarray(rng.randint(0, V, size=(8, 5)).astype(np.int32))
    tgt = jnp.asarray(rng.randint(0, V, size=(8, 5)).astype(np.int32))
    n_micro = 4

    def loss_dp(params):
        return _lm_loss(_lm_sequential(params, tok), tgt)

    fn = _lm_pipeline_fn(mesh, n_micro, remat=remat)
    specs = {"emb": P(), "w": P("pipe"), "b": P("pipe"), "out": P()}
    sharded = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs)
    micro_tok = split_microbatches(tok, n_micro)
    micro_tgt = split_microbatches(tgt, n_micro)

    def loss_pp(params):
        logits = fn(params, micro_tok)
        return _lm_loss(merge_microbatches(logits),
                        merge_microbatches(micro_tgt))

    l_ref, g_ref = jax.value_and_grad(loss_dp)(params)
    l_pp, g_pp = jax.jit(jax.value_and_grad(loss_pp))(sharded)
    np.testing.assert_allclose(float(l_pp), float(l_ref), rtol=1e-5)
    for k in ("emb", "w", "b", "out"):
        np.testing.assert_allclose(np.asarray(g_pp[k]), np.asarray(g_ref[k]),
                                   rtol=1e-4, atol=1e-5)


def _1f1b_fn(mesh):
    from horovod_tpu.parallel.pipeline import pipeline_train_1f1b

    def body(params, micro_tok, micro_tgt):
        local = {"w": params["w"][0], "b": params["b"][0]}
        loss, gs, gf, gl = pipeline_train_1f1b(
            _tblock_fn, local, micro_tok, micro_tgt, _lm_loss,
            "pipe", N_STAGES,
            first_fn=_embed_fn, first_params={"emb": params["emb"]},
            last_fn=_head_fn, last_params={"out": params["out"]})
        # restack per-stage grads on a leading axis for the out_spec
        gs = jax.tree_util.tree_map(lambda a: a[None], gs)
        return loss, gs, gf, gl

    specs = {"emb": P(), "w": P("pipe"), "b": P("pipe"), "out": P()}
    return jax.shard_map(
        body, mesh=mesh, in_specs=(specs, P(), P()),
        out_specs=(P(), {"w": P("pipe"), "b": P("pipe")},
                   {"emb": P()}, {"out": P()}),
        check_vma=False), specs


@pytest.mark.parametrize("n_micro", [4, 8])
def test_1f1b_matches_unpipelined(n_micro):
    """The hand-scheduled 1F1B must reproduce the unpipelined loss AND all
    gradients (stage, embedding, head) exactly — same bar as the AD
    fill-drain pipeline (VERDICT r4 item 4)."""
    mesh = _mesh()
    params = _lm_params(seed=7)
    rng = np.random.RandomState(8)
    tok = jnp.asarray(rng.randint(0, V, size=(8, 5)).astype(np.int32))
    tgt = jnp.asarray(rng.randint(0, V, size=(8, 5)).astype(np.int32))

    def loss_dp(params):
        return _lm_loss(_lm_sequential(params, tok), tgt)

    l_ref, g_ref = jax.value_and_grad(loss_dp)(params)

    fn, specs = _1f1b_fn(mesh)
    sharded = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs)
    loss, gs, gf, gl = jax.jit(fn)(
        sharded, split_microbatches(tok, n_micro),
        split_microbatches(tgt, n_micro))
    np.testing.assert_allclose(float(loss), float(l_ref), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gs["w"]), np.asarray(g_ref["w"]),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gs["b"]), np.asarray(g_ref["b"]),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gf["emb"]),
                               np.asarray(g_ref["emb"]),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gl["out"]),
                               np.asarray(g_ref["out"]),
                               rtol=1e-4, atol=1e-5)


def test_1f1b_memory_bounded_in_n_micro():
    """THE property 1F1B buys (VERDICT r4 item 4): peak live-activation
    memory is O(n_stages), independent of n_micro — where AD through the
    fill-drain scan keeps O(n_micro) live. Compare compiled peak temp
    memory at n_micro=8 vs 32: 1F1B must stay roughly flat while the
    AD pipeline grows several-fold."""
    mesh = _mesh()
    params = _lm_params(seed=9)
    fn, specs = _1f1b_fn(mesh)
    sharded_specs = jax.tree_util.tree_map(
        lambda x, s: jax.ShapeDtypeStruct(
            x.shape, x.dtype, sharding=NamedSharding(mesh, s)),
        params, specs)

    def peak_temp(n_micro, mb=4, t=128):
        tok = jax.ShapeDtypeStruct((n_micro, mb, t), jnp.int32,
                                   sharding=NamedSharding(mesh, P()))
        comp = jax.jit(fn).lower(sharded_specs, tok, tok).compile()
        ma = comp.memory_analysis()
        if ma is None or not hasattr(ma, "temp_size_in_bytes"):
            pytest.skip("memory_analysis not supported on this backend")
        return ma.temp_size_in_bytes

    small, big = peak_temp(8), peak_temp(32)
    # 4x the microbatches must NOT mean 4x the activation memory; allow
    # slack for per-tick bookkeeping but reject O(n_micro) growth
    assert big < small * 1.7, (small, big)

    # contrast: AD through the fill-drain pipeline DOES grow O(n_micro)
    def ad_fn(mesh):
        fd = _lm_pipeline_fn(mesh, None, remat=True)

        def loss_pp(params, micro_tok, micro_tgt):
            logits = fd(params, micro_tok)
            return _lm_loss(merge_microbatches(logits),
                            merge_microbatches(micro_tgt))

        return jax.grad(loss_pp)

    def ad_peak(n_micro, mb=4, t=128):
        tok = jax.ShapeDtypeStruct((n_micro, mb, t), jnp.int32)
        comp = jax.jit(ad_fn(mesh)).lower(sharded_specs, tok, tok).compile()
        ma = comp.memory_analysis()
        if ma is None or not hasattr(ma, "temp_size_in_bytes"):
            pytest.skip("memory_analysis not supported on this backend")
        return ma.temp_size_in_bytes

    ad_small, ad_big = ad_peak(8), ad_peak(32)
    assert ad_big > ad_small * 2.0, \
        "expected the AD fill-drain pipeline to grow with n_micro " \
        f"({ad_small} -> {ad_big}); if this stopped holding, revisit the " \
        "1f1b docstring's memory claim"


def test_pipeline_bubbles_are_skipped():
    """Bubble ticks must be genuine runtime conditionals (XLA skips the
    stage compute), not masked always-computed work; and the schedule's
    bubble fraction follows the fill-drain formula."""
    from horovod_tpu.parallel.pipeline import pipeline_bubble_fraction
    assert pipeline_bubble_fraction(4, 4) == pytest.approx(3 / 7)
    assert pipeline_bubble_fraction(1, 8) == 0.0
    mesh = _mesh()
    fn = jax.jit(_pipeline_fn(mesh))
    params = jax.device_put(_stacked_params(),
                            NamedSharding(mesh, P("pipe")))
    micro = split_microbatches(
        jnp.zeros((16, D), jnp.float32), 4)
    txt = fn.lower(params, micro).compile().as_text()
    assert "conditional" in txt, \
        "pipeline ticks compile without a runtime conditional (bubble " \
        "ticks would do masked wasted work)"


@pytest.mark.parametrize("remat", ["none", "block"])
def test_pp_flagship_matches_single_device(remat):
    """The REAL flagship through the 1F1B pipeline (embed -> 4 layers over
    4 stages -> tied-embedding head + lean logsumexp loss) must reproduce
    the monolithic single-device training step: same loss and same
    updated params after one SGD step — with and without per-layer remat
    inside the stage recompute."""
    import optax
    from horovod_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                                n_layers=4, d_ff=64, max_seq=16,
                                dtype=jnp.float32, attention="flash",
                                remat=remat)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(11)
    inputs = jnp.asarray(rng.randint(0, 64, size=(8, 16)).astype(np.int32))
    targets = jnp.asarray(rng.randint(0, 64, size=(8, 16)).astype(np.int32))

    # single-device reference step
    opt = optax.sgd(0.1)
    ref_state = opt.init(params)
    l_ref, g_ref = jax.value_and_grad(
        lambda p: tfm.lean_lm_loss(p, inputs, targets, cfg))(params)
    up, _ = opt.update(g_ref, ref_state, params)
    p_ref = optax.apply_updates(params, up)

    # 4-stage 1F1B pipeline
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:4]), (tfm.PIPE_AXIS,))
    specs = tfm.pp_param_specs(cfg)
    sharded = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params,
        specs)
    step = tfm.make_pp_train_step(mesh, cfg, optax.sgd(0.1), n_micro=4)
    p_pp, _, l_pp = step(sharded, optax.sgd(0.1).init(sharded), inputs,
                         targets)
    np.testing.assert_allclose(float(l_pp), float(l_ref), rtol=1e-5)
    for k in ("embed", "ln_f"):
        np.testing.assert_allclose(np.asarray(p_pp[k]),
                                   np.asarray(p_ref[k]), rtol=1e-4,
                                   atol=1e-5)
    for k in p_ref["layers"]:
        np.testing.assert_allclose(np.asarray(p_pp["layers"][k]),
                                   np.asarray(p_ref["layers"][k]),
                                   rtol=1e-4, atol=1e-5)


def test_pp_flagship_composes_with_dp():
    """DP x PP over a (data=2, pipe=4) mesh: the global batch splits over
    the data axis, each replica pipelines its half, gradients pmean over
    data — the result must match the single-device full-batch step."""
    import optax
    from horovod_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                                n_layers=4, d_ff=64, max_seq=16,
                                dtype=jnp.float32, attention="flash")
    params = tfm.init_params(jax.random.PRNGKey(1), cfg)
    rng = np.random.RandomState(12)
    inputs = jnp.asarray(rng.randint(0, 64, size=(8, 16)).astype(np.int32))
    targets = jnp.asarray(rng.randint(0, 64, size=(8, 16)).astype(np.int32))

    opt = optax.sgd(0.1)
    l_ref, g_ref = jax.value_and_grad(
        lambda p: tfm.lean_lm_loss(p, inputs, targets, cfg))(params)
    up, _ = opt.update(g_ref, opt.init(params), params)
    p_ref = optax.apply_updates(params, up)

    mesh = jax.sharding.Mesh(
        np.array(jax.devices()[:8]).reshape(2, 4),
        (tfm.DATA_AXIS, tfm.PIPE_AXIS))
    specs = tfm.pp_param_specs(cfg)
    sharded = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params,
        specs)
    step = tfm.make_pp_train_step(mesh, cfg, optax.sgd(0.1), n_micro=2)
    tok_sh = NamedSharding(mesh, P(tfm.DATA_AXIS))
    p_pp, _, l_pp = step(sharded, optax.sgd(0.1).init(sharded),
                         jax.device_put(inputs, tok_sh),
                         jax.device_put(targets, tok_sh))
    np.testing.assert_allclose(float(l_pp), float(l_ref), rtol=1e-5)
    for k in ("embed", "ln_f"):
        np.testing.assert_allclose(np.asarray(p_pp[k]),
                                   np.asarray(p_ref[k]), rtol=1e-4,
                                   atol=1e-5)
    for k in p_ref["layers"]:
        np.testing.assert_allclose(np.asarray(p_pp["layers"][k]),
                                   np.asarray(p_ref["layers"][k]),
                                   rtol=1e-4, atol=1e-5)
