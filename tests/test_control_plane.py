"""Replicated control plane (ISSUE 12): endpoint-set client + circuit
breaker, journaled replication with quorum acks, lease/epoch promotion,
fencing, backpressure — and the chaos proofs that SIGKILL of the primary
KV root costs a sub-second failover, never the fleet.

Two tiers:

- unit tests (fast, in-process): endpoint parsing/breaker/redirect rules,
  server snapshot/backpressure surfaces, replication/ack/fencing semantics,
  journal audit + the new failpoints;
- ``chaos``-marked tests that really ``SIGKILL`` a subprocess primary
  mid-elastic-registration, mid-chunked-shard-upload, and mid-long-poll —
  each must complete through the promoted standby with no acked-write loss
  (verified by the journal sequence audit), plus the acceptance run: an
  elastic training loop whose telemetry rides a 1-primary/1-standby
  control plane survives the root kill with failover counters visible in
  the standby's scrape.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu import faults
from horovod_tpu.metrics import publish_snapshot, registry
from horovod_tpu.runner.http_client import (Endpoints, KVBackpressure,
                                            parse_endpoint_spec,
                                            put_data_into_kvstore,
                                            put_large_value,
                                            read_data_from_kvstore,
                                            read_large_value,
                                            resolve_endpoints)
from horovod_tpu.runner.http_server import KVStoreServer, find_free_port
from horovod_tpu.runner.replication import ReplicationConfig

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# fast-promotion settings every in-process pair in this file uses
FAST = dict(lease_timeout=0.3, lease_interval=0.1)


@pytest.fixture(autouse=True)
def _disarm():
    faults.disarm()
    yield
    faults.disarm()


def _pair(role_b="standby", cfg=None, cfg_b=None):
    """An in-process primary+standby pair on fixed free ports. Returns
    (server_a, server_b, endpoints, replica_specs)."""
    p1, p2 = find_free_port(), find_free_port()
    a = KVStoreServer(("127.0.0.1", p1))
    b = KVStoreServer(("127.0.0.1", p2))
    a.start()
    b.start()
    reps = [f"127.0.0.1:{p1}", f"127.0.0.1:{p2}"]
    a.enable_replication(reps[0], reps, role="primary",
                         config=cfg or ReplicationConfig(**FAST))
    b.enable_replication(reps[1], reps, role=role_b,
                         config=cfg_b or cfg or ReplicationConfig(**FAST))
    eps = Endpoints([("127.0.0.1", p1), ("127.0.0.1", p2)],
                    trip_failures=3, reset_delay=0.1)
    return a, b, eps, reps


def _repl_put(port, key, payload, timeout=5):
    """Raw replication-control PUT — lets a test play the role of a
    (possibly dead or zombie) primary on the wire."""
    import urllib.request
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/_repl/{key}",
        data=json.dumps(payload).encode(), method="PUT")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read() or b"{}")


def _entry(seq, sseq, scope, key, value, epoch=1):
    import base64
    return {"seq": seq, "sseq": sseq, "epoch": epoch, "scope": scope,
            "op": "put", "key": key,
            "value": base64.b64encode(value).decode()}


# ---------------------------------------------------------------------------
# Endpoint set + circuit breaker (client tier)
# ---------------------------------------------------------------------------

class TestEndpoints:
    def test_spec_parsing_forms(self):
        assert parse_endpoint_spec("h1:1,h2:2") == (("h1", 1), ("h2", 2))
        assert parse_endpoint_spec("h1", default_port=7) == (("h1", 7),)
        with pytest.raises(ValueError):
            parse_endpoint_spec("h1", default_port=None)
        with pytest.raises(ValueError):
            parse_endpoint_spec("")

    def test_resolve_accepts_legacy_tuple_as_addr(self):
        """The documented legacy form: the whole ('host', port) tuple in
        the addr position (arm_from_kv callers) resolves to the same
        shared single-endpoint set, not a pairs-list unpack crash."""
        a = resolve_endpoints(("127.0.0.1", 12347))
        assert a.pairs == (("127.0.0.1", 12347),)
        assert resolve_endpoints("127.0.0.1", 12347) is a

    def test_resolve_is_shared_and_stateful(self):
        """Callers passing raw (addr, port) tuples every call must land on
        the SAME Endpoints, so breaker state survives stateless call
        sites; every accepted form of the same pair set aliases it."""
        a = resolve_endpoints("127.0.0.1", 12345)
        b = resolve_endpoints("127.0.0.1:12345", None)
        c = resolve_endpoints([("127.0.0.1", 12345)])
        assert a is b is c
        assert resolve_endpoints(a) is a
        d = resolve_endpoints("127.0.0.1:12345,127.0.0.1:12346")
        assert d is not a and len(d) == 2

    def test_breaker_trips_and_half_opens(self):
        eps = Endpoints([("h1", 1), ("h2", 2)], trip_failures=2,
                        reset_delay=0.1)
        assert eps.candidates() == [0, 1]
        eps.record_failure(0)
        assert eps.candidates() == [0, 1]      # below the trip threshold
        eps.record_failure(0)                  # trips open
        assert eps.candidates() == [1, 0]      # open sorts last, not skipped
        # past the reopen delay the breaker half-opens: the endpoint is a
        # plain candidate again (one probe), and a success closes it
        time.sleep(0.35)
        assert eps.candidates()[0] == 0        # preferred again (half-open)
        eps.record_success(0)
        assert eps.candidates() == [0, 1]

    def test_all_open_still_served(self):
        """With every breaker tripped there is nothing better to try:
        candidates() serves the full set anyway (ordered by soonest
        reopen — jittered, so only membership is asserted)."""
        eps = Endpoints([("h1", 1), ("h2", 2)], trip_failures=1,
                        reset_delay=5.0)
        eps.record_failure(0)
        eps.record_failure(1)
        assert sorted(eps.candidates()) == [0, 1]

    def test_redirect_is_epoch_aware(self):
        eps = Endpoints([("h1", 1), ("h2", 2)])
        assert eps.record_redirect("h2:2", epoch=3) == 1
        assert eps.candidates()[0] == 1
        # a zombie's stale hint (older epoch) must not steal it back
        assert eps.record_redirect("h1:1", epoch=2) is None
        assert eps.candidates()[0] == 1
        # unknown hints never grow the frozen set
        assert eps.record_redirect("h9:9", epoch=9) is None

    def test_success_on_standby_read_does_not_steal_preference(self):
        eps = Endpoints([("h1", 1), ("h2", 2)])
        eps.record_redirect("h2:2", epoch=1)
        eps.record_success(0, prefer=False)    # a GET served by h1
        assert eps.candidates()[0] == 1


# ---------------------------------------------------------------------------
# Server surfaces: snapshot + backpressure
# ---------------------------------------------------------------------------

class TestServerSurfaces:
    def test_snapshot_is_consistent_copy(self):
        s = KVStoreServer(("127.0.0.1", 0))
        s.start()
        try:
            put_data_into_kvstore("127.0.0.1", s.port, "a", "k", b"v")
            snap = s.snapshot()
            assert snap == {"a": {"k": b"v"}}
            snap["a"]["k"] = b"mutated"        # a COPY, not the live store
            assert s.snapshot()["a"]["k"] == b"v"
            s.clear_all()
            assert s.snapshot() == {}
        finally:
            s.stop()

    def test_backpressure_429_and_retry_after(self):
        reg = registry()
        s = KVStoreServer(("127.0.0.1", 0))
        s.start()
        s.set_scope_budget("metrics", 10)
        bp_before = reg.counter("hvd_tpu_kv_backpressure_total").value(
            scope="metrics")
        try:
            put_data_into_kvstore("127.0.0.1", s.port, "metrics", "0",
                                  b"12345678", timeout=5)
            with pytest.raises(KVBackpressure) as ei:
                put_data_into_kvstore("127.0.0.1", s.port, "metrics", "1",
                                      b"12345678", timeout=5)
            assert ei.value.retry_after > 0
            assert reg.counter("hvd_tpu_kv_backpressure_total").value(
                scope="metrics") == bp_before + 1
            # same-key overwrite that shrinks (or holds) the scope always
            # passes — a last-writer-wins publisher can't wedge itself
            put_data_into_kvstore("127.0.0.1", s.port, "metrics", "0",
                                  b"1234", timeout=5)
            # other scopes are unaffected
            put_data_into_kvstore("127.0.0.1", s.port, "other", "k",
                                  b"x" * 64, timeout=5)
        finally:
            s.stop()

    def test_backpressure_is_not_retried(self):
        """KVBackpressure is not an OSError: the retry machinery must not
        hammer a server that asked for shedding."""
        reg = registry()
        s = KVStoreServer(("127.0.0.1", 0))
        s.start()
        s.set_scope_budget("sc", 4)
        retries_before = reg.counter("hvd_tpu_kv_retries_total").total()
        try:
            with pytest.raises(KVBackpressure):
                put_data_into_kvstore("127.0.0.1", s.port, "sc", "k",
                                      b"way too big", timeout=5, retries=3)
            assert reg.counter("hvd_tpu_kv_retries_total").total() \
                == retries_before
        finally:
            s.stop()

    def test_publishers_shed_oldest_first_not_block(self):
        """The metrics/trace/stall publishers honor 429 by shedding (the
        ring/last-writer-wins semantics make the loss oldest-first) and
        counting hvd_tpu_kv_shed_bytes_total — never raising, never
        blocking the step path."""
        from horovod_tpu.stall_inspector import StallInspector
        from horovod_tpu.trace import publish_segment
        reg = registry()
        s = KVStoreServer(("127.0.0.1", 0))
        s.start()
        for scope in ("metrics", "trace", "stall"):
            s.set_scope_budget(scope, 8)
        kv = ("127.0.0.1", s.port)
        shed = lambda sc: reg.counter("hvd_tpu_kv_shed_bytes_total").value(
            scope=sc)
        before = {sc: shed(sc) for sc in ("metrics", "trace", "stall")}
        try:
            publish_snapshot(kv, 0, {"enabled": True,
                                     "counters": {"x": 1}})   # > 8 bytes
            assert shed("metrics") > before["metrics"]
            publish_segment(kv, 0, b"{" + b"x" * 64 + b"}")
            assert shed("trace") > before["trace"]
            insp = StallInspector(warning_seconds=1e9, check_interval=1e3,
                                  kv=kv, rank=0, size=2)
            try:
                insp._publish()
                assert shed("stall") > before["stall"]
                # deliberate shedding is not an outage: no failure streak
                assert insp._pub_fail_streak == 0
            finally:
                insp.stop()
            assert s.snapshot() == {}          # nothing landed, by design
        finally:
            s.stop()


# ---------------------------------------------------------------------------
# Replication semantics (in-process pair)
# ---------------------------------------------------------------------------

class TestReplication:
    def test_acked_write_visible_on_standby(self):
        a, b, eps, _ = _pair()
        try:
            put_data_into_kvstore(eps, None, "reg", "k", b"v", timeout=10)
            # quorum-acked means applied on the standby BEFORE the ack
            assert b.snapshot()["reg"]["k"] == b"v"
            assert a.replication.status()["role"] == "primary"
            assert b.replication.status()["applied_seq"] == 1
        finally:
            a.stop()
            b.stop()

    def test_standby_redirects_writes_to_primary(self):
        """A client whose endpoint set lists the standby FIRST still
        lands its write on the primary via the 409 hint."""
        a, b, _, reps = _pair()
        backwards = Endpoints([("127.0.0.1", b.port),
                               ("127.0.0.1", a.port)], reset_delay=0.1)
        try:
            put_data_into_kvstore(backwards, None, "sc", "k", b"v",
                                  timeout=10)
            assert a.snapshot()["sc"]["k"] == b"v"
            assert b.snapshot()["sc"]["k"] == b"v"
        finally:
            a.stop()
            b.stop()

    def test_standby_budget_never_terminal_429s_a_redirect(self):
        """The budget is the PRIMARY's to enforce: a standby with a
        local/stale budget must redirect (409) rather than answer 429 —
        KVBackpressure is deliberately terminal for the client, and a
        standby-first endpoint order must not turn an acceptable write
        into a refusal."""
        a, b, _, _ = _pair()
        b.set_scope_budget("ckptshard", 4)     # standby-local budget
        backwards = Endpoints([("127.0.0.1", b.port),
                               ("127.0.0.1", a.port)], reset_delay=0.1)
        try:
            put_data_into_kvstore(backwards, None, "ckptshard", "g1.c0",
                                  b"x" * 64, timeout=10)
            assert a.snapshot()["ckptshard"]["g1.c0"] == b"x" * 64
        finally:
            a.stop()
            b.stop()

    def test_standby_serves_long_poll_reads(self):
        a, b, eps, _ = _pair()
        standby_only = Endpoints([("127.0.0.1", b.port)], reset_delay=0.1)
        got = {}

        def _reader():
            got["v"] = read_data_from_kvstore(standby_only, None, "sc",
                                              "late", timeout=10,
                                              poll_interval=0.05)

        t = threading.Thread(target=_reader)
        t.start()
        try:
            time.sleep(0.2)
            put_data_into_kvstore(eps, None, "sc", "late", b"polled",
                                  timeout=10)
            t.join(timeout=10)
            assert got.get("v") == b"polled"
        finally:
            a.stop()
            b.stop()

    def test_delete_and_clear_replicate(self):
        a, b, eps, _ = _pair()
        try:
            put_data_into_kvstore(eps, None, "sc", "k", b"v", timeout=10)
            from horovod_tpu.runner.http_client import \
                delete_data_from_kvstore
            delete_data_from_kvstore(eps, None, "sc", "k", timeout=10)
            assert "k" not in b.snapshot().get("sc", {})
            put_data_into_kvstore(eps, None, "trace", "0", b"x", timeout=10)
            a.clear_scope("trace")
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and \
                    b.snapshot().get("trace"):
                time.sleep(0.05)
            assert not b.snapshot().get("trace")
        finally:
            a.stop()
            b.stop()

    def test_replicate_failpoint_degrades_quorum_loudly(self, caplog):
        """kv.replicate=*raise models a dead standby link: writes degrade
        to fewer replicas after the suspect streak — loudly — instead of
        blocking forever (the 1+1 availability rule). An explicit
        HOROVOD_KV_ACK_REPLICAS stays a hard requirement."""
        import logging
        a, b, eps, _ = _pair()
        try:
            faults.arm("kv.replicate=*raise(ConnectionError)")
            with caplog.at_level(logging.WARNING,
                                 logger="horovod_tpu.runner"):
                put_data_into_kvstore(eps, None, "sc", "k", b"v",
                                      timeout=20)
            assert a.snapshot()["sc"]["k"] == b"v"
            assert "k" not in b.snapshot().get("sc", {})  # never replicated
            assert any("DEGRADED" in r.message for r in caplog.records)
            assert faults.hits("kv.replicate") >= 3
        finally:
            faults.disarm()
            a.stop()
            b.stop()

    def test_strict_ack_replicas_never_degrades(self):
        cfg = ReplicationConfig(ack_replicas=2, **FAST)
        a, b, eps, _ = _pair(cfg=cfg)
        try:
            faults.arm("kv.replicate=*raise(ConnectionError)")
            with pytest.raises((OSError, TimeoutError)):
                put_data_into_kvstore(eps, None, "sc", "k", b"v",
                                      timeout=3)
        finally:
            faults.disarm()
            a.stop()
            b.stop()

    def test_fencing_rejects_zombie_and_demotes_it(self):
        """The fencing proof: the old primary comes back (here: never
        died, just got leapfrogged by a manual promotion) and its
        stale-epoch stream is rejected; it demotes itself, resyncs, and
        the acked write lands through the new primary."""
        reg = registry()
        cfg = ReplicationConfig(lease_timeout=60, lease_interval=0.1)
        a, b, eps, reps = _pair(cfg=cfg)
        fenced_before = reg.counter("hvd_tpu_kv_fenced_writes_total").total()
        promo_before = reg.counter("hvd_tpu_kv_promotions_total").total()
        try:
            put_data_into_kvstore(eps, None, "sc", "pre", b"1", timeout=10)
            b.replication.promote()            # epoch 2; A is a zombie now
            assert reg.counter("hvd_tpu_kv_promotions_total").total() \
                == promo_before + 1
            # the write first hits the zombie (sticky preference), which
            # cannot ack (fenced by B) — the client lands it on B
            put_data_into_kvstore(eps, None, "sc", "fenced", b"2",
                                  timeout=15)
            assert b.snapshot()["sc"]["fenced"] == b"2"
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and \
                    a.replication.status()["role"] != "standby":
                time.sleep(0.05)
            st = a.replication.status()
            assert st["role"] == "standby" and st["epoch"] == 2
            assert reg.counter("hvd_tpu_kv_fenced_writes_total").total() \
                > fenced_before
            # the demoted zombie resyncs the acked state from B's stream
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and \
                    a.snapshot().get("sc", {}).get("fenced") != b"2":
                time.sleep(0.05)
            assert a.snapshot()["sc"]["fenced"] == b"2"
            # and a raw stale-epoch apply is refused with 412
            import urllib.error
            import urllib.request
            req = urllib.request.Request(
                f"http://127.0.0.1:{b.port}/_repl/apply",
                data=json.dumps({"epoch": 1, "base": None, "entries": [],
                                 "primary": reps[0]}).encode(),
                method="PUT")
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=5)
            assert ei.value.code == 412
        finally:
            a.stop()
            b.stop()

    def test_promotion_audits_journal_and_counts_gaps(self):
        reg = registry()
        cfg = ReplicationConfig(lease_timeout=60, lease_interval=0.1)
        a, b, eps, _ = _pair(cfg=cfg)
        gaps_before = reg.counter("hvd_tpu_kv_journal_gaps_total").total()
        try:
            for i in range(4):
                put_data_into_kvstore(eps, None, "reg", f"k{i}",
                                      f"v{i}".encode(), timeout=10)
            audit = b.replication.audit_journal()
            assert audit["gaps"] == [] and audit["entries"] == 4
            # the kv.journal_gap failpoint injects a synthetic gap, so the
            # detection path (count + promote-time ERROR) is provable
            faults.arm("kv.journal_gap=1*drop()")
            audit = b.replication.audit_journal()
            assert audit["gaps"] and "injected" in audit["gaps"][0]
            assert reg.counter("hvd_tpu_kv_journal_gaps_total").total() \
                > gaps_before
            faults.disarm()
            # kv.promote fires on the promotion edge
            faults.arm("kv.promote=1*noop()")
            b.replication.promote()
            assert faults.hits("kv.promote") == 1
            assert b.replication.status()["role"] == "primary"
        finally:
            faults.disarm()
            a.stop()
            b.stop()

    def test_idle_primary_keeps_lease_alive(self):
        """An IDLE control plane (no client writes) must not flip-flop:
        the lease tick sends an empty apply even when the standby is
        fully caught up, so a healthy-but-quiet primary is never
        spuriously leapfrogged."""
        a, b, eps, _ = _pair()                 # lease_timeout=0.3
        try:
            put_data_into_kvstore(eps, None, "sc", "k", b"v", timeout=10)
            time.sleep(1.5)                    # >> every promotion grace
            assert a.replication.status()["role"] == "primary"
            st = b.replication.status()
            assert st["role"] == "standby" and st["epoch"] == 1, st
        finally:
            a.stop()
            b.stop()

    def test_rendezvous_addr_env_carries_comma_spec(self):
        """The worker rendezvous path passes HOROVOD_GLOO_RENDEZVOUS_ADDR
        + an int port straight into the client — an addr that carries the
        comma spec must resolve to the set (port ignored), per
        docs/elastic.md."""
        a, b, _, reps = _pair()
        try:
            spec = ",".join(reps)
            put_data_into_kvstore(spec, 12345, "rendezvous", "k", b"v",
                                  timeout=10)
            assert read_data_from_kvstore(spec, 12345, "rendezvous", "k",
                                          timeout=10) == b"v"
            assert b.snapshot()["rendezvous"]["k"] == b"v"
        finally:
            a.stop()
            b.stop()

    def test_simultaneous_promotions_tie_break_by_index(self):
        """Two standbys of a dead root promoting inside the same window
        land on the SAME epoch — the replica-set index tie-break must
        demote exactly one (the higher index), never leave a permanent
        dual primary."""
        ports = [find_free_port() for _ in range(3)]
        reps = [f"127.0.0.1:{p}" for p in ports]
        cfg = ReplicationConfig(lease_timeout=60, lease_interval=0.1)
        servers = []
        try:
            for i, p in enumerate(ports):
                s = KVStoreServer(("127.0.0.1", p))
                s.start()
                s.enable_replication(
                    reps[i], reps, role="primary" if i == 0 else "standby",
                    config=cfg)
                servers.append(s)
            eps = Endpoints([("127.0.0.1", p) for p in ports],
                            reset_delay=0.1)
            put_data_into_kvstore(eps, None, "sc", "pre", b"1", timeout=10)
            servers[0].stop()                  # the root dies...
            servers[1].replication.promote()   # ...and BOTH standbys
            servers[2].replication.promote()   # promote to epoch 2
            deadline = time.monotonic() + 8
            while time.monotonic() < deadline:
                roles = [s.replication.status()["role"]
                         for s in servers[1:]]
                if roles == ["primary", "standby"]:
                    break
                time.sleep(0.05)
            assert [s.replication.status()["role"]
                    for s in servers[1:]] == ["primary", "standby"]
            # the surviving pair still serves acked writes
            put_data_into_kvstore(eps, None, "sc", "post", b"2",
                                  timeout=15)
            assert servers[1].snapshot()["sc"]["post"] == b"2"
        finally:
            for s in servers[1:]:
                s.stop()

    def test_clear_scope_refusal_is_loud_on_standby(self, caplog):
        import logging
        a, b, eps, _ = _pair(cfg=ReplicationConfig(lease_timeout=60,
                                                   lease_interval=0.1))
        try:
            put_data_into_kvstore(eps, None, "trace", "0", b"x", timeout=10)
            with caplog.at_level(logging.WARNING,
                                 logger="horovod_tpu.runner"):
                b.clear_scope("trace")         # a standby cannot clear
            assert any("clear_scope" in r.message for r in caplog.records)
            assert b.snapshot().get("trace")   # nothing silently dropped
        finally:
            a.stop()
            b.stop()

    def test_arm_from_kv_through_surviving_replica(self):
        """Satellite: chaos scripts arm faults through a surviving
        replica after a root kill — arm_from_kv takes the endpoint set
        and reads from whichever replica answers."""
        a, b, eps, _ = _pair(cfg=ReplicationConfig(**FAST))
        try:
            put_data_into_kvstore(eps, None, "faults", "spec",
                                  b"test.cp_arm=2*noop()", timeout=10)
            a.stop()                           # root gone; standby serves
            assert faults.arm_from_kv(eps, timeout=10) is True
            faults.failpoint("test.cp_arm")
            assert faults.hits("test.cp_arm") == 1
        finally:
            faults.disarm()
            b.stop()


# ---------------------------------------------------------------------------
# Failover-correctness regressions (review findings): ahead-peer
# divergence, election restriction, degraded-ack accounting, lagging-peer
# streaks, replicated elastic-init clears.
# ---------------------------------------------------------------------------

class TestFailoverCorrectness:
    def test_ahead_peer_truncated_never_counted_as_synced(self):
        """A peer whose applied seq runs AHEAD of the primary (a dead
        root replicated further to it before the failover) must be
        snapshot-resynced — tail truncated, loudly — never treated as
        fully synced: counting it would fake quorum acks while its
        read-serving store silently diverges forever."""
        reg = registry()
        lost_before = reg.counter(
            "hvd_tpu_kv_acked_writes_lost_total").total()
        a, b, eps, reps = _pair()              # FAST heartbeats
        try:
            put_data_into_kvstore(eps, None, "reg", "k1", b"v1",
                                  timeout=10)
            assert b.replication.status()["applied_seq"] == 1
            # inject a divergent tail on B, as if a prior reign had
            # replicated seqs 2..3 to B but never to A
            _repl_put(b.port, "apply", {
                "epoch": 1, "base": 1, "primary": reps[0],
                "entries": [_entry(2, 1, "ghost", "g1", b"x"),
                            _entry(3, 2, "ghost", "g2", b"y")]})
            assert b.replication.status()["applied_seq"] == 3
            # A's next heartbeat sees B ahead and truncates it back (the
            # loss counter lands only after A reads the push response —
            # a beat after B's store resets — so poll for both)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and (
                    b.replication.status()["applied_seq"] != 1 or
                    reg.counter("hvd_tpu_kv_acked_writes_lost_total")
                    .total() < lost_before + 2):
                time.sleep(0.05)
            assert b.replication.status()["applied_seq"] == 1
            assert "ghost" not in b.snapshot()
            assert reg.counter(
                "hvd_tpu_kv_acked_writes_lost_total").total() \
                >= lost_before + 2
            # and the pair converges on new acked writes
            put_data_into_kvstore(eps, None, "reg", "k2", b"v2",
                                  timeout=10)
            assert b.snapshot()["reg"]["k2"] == b"v2"
            assert b.replication.status()["applied_seq"] == \
                a.replication.status()["seq"]
        finally:
            a.stop()
            b.stop()

    def test_election_restriction_pulls_tail_from_more_applied_peer(self):
        """A write replicated to standby-2 but not standby-1 when the
        root dies must survive standby-1's (earlier-staggered) automatic
        promotion: the candidate pulls the journal tail from the
        more-applied peer BEFORE promoting, instead of winning on index
        order and losing a quorum-acked write."""
        dead = f"127.0.0.1:{find_free_port()}"   # the SIGKILLed root
        pb, pc = find_free_port(), find_free_port()
        b = KVStoreServer(("127.0.0.1", pb))
        c = KVStoreServer(("127.0.0.1", pc))
        b.start()
        c.start()
        reps = [dead, f"127.0.0.1:{pb}", f"127.0.0.1:{pc}"]
        try:
            # C (index 2) saw seqs 1..3 from the dead root; B (index 1,
            # promotes first) only 1..2 — seq 3 was quorum-acked on
            # {root, C} and must not be lost
            c.enable_replication(
                reps[2], reps, role="standby",
                config=ReplicationConfig(lease_timeout=60,
                                         lease_interval=0.1))
            entries = [_entry(i, i, "reg", f"k{i}", f"v{i}".encode())
                       for i in (1, 2, 3)]
            _repl_put(pc, "apply", {"epoch": 1, "base": 0,
                                    "primary": dead, "entries": entries})
            b.enable_replication(reps[1], reps, role="standby",
                                 config=ReplicationConfig(**FAST))
            _repl_put(pb, "apply", {"epoch": 1, "base": 0,
                                    "primary": dead,
                                    "entries": entries[:2]})
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and \
                    b.replication.status()["role"] != "primary":
                time.sleep(0.05)
            st = b.replication.status()
            assert st["role"] == "primary" and st["epoch"] >= 2
            assert st["applied_seq"] == 3      # caught up BEFORE promoting
            assert b.snapshot()["reg"]["k3"] == b"v3"
            assert b.replication.audit_journal()["gaps"] == []
        finally:
            b.stop()
            c.stop()

    def test_degraded_acks_counted_lost_on_demotion(self, caplog):
        """Acks granted while the quorum was degraded (peer SUSPECT) are
        NOT 'never reached quorum': on fencing they are counted
        (hvd_tpu_kv_acked_writes_lost_total) and logged at ERROR —
        reported, never asserted away."""
        import logging
        reg = registry()
        lost_before = reg.counter(
            "hvd_tpu_kv_acked_writes_lost_total").total()
        cfg = ReplicationConfig(lease_timeout=60, lease_interval=30)
        a, b, eps, reps = _pair(cfg=cfg)
        try:
            put_data_into_kvstore(eps, None, "sc", "pre", b"1", timeout=10)
            faults.arm("kv.replicate=*raise(ConnectionError)")
            put_data_into_kvstore(eps, None, "sc", "deg", b"2", timeout=30)
            assert a.replication.degraded_ack_seqs
            with caplog.at_level(logging.ERROR,
                                 logger="horovod_tpu.runner"):
                # B's post-promotion stream, on the wire: fences A
                _repl_put(a.port, "apply", {"epoch": 2, "base": None,
                                            "primary": reps[1],
                                            "entries": []})
            st = a.replication.status()
            assert st["role"] == "standby" and st["epoch"] == 2
            assert reg.counter(
                "hvd_tpu_kv_acked_writes_lost_total").total() > lost_before
            assert any("DEGRADED quorum" in r.message
                       for r in caplog.records)
        finally:
            faults.disarm()
            a.stop()
            b.stop()

    def test_lagging_answering_peer_keeps_full_quorum(self):
        """Only transport-level failures accrue the SUSPECT streak: a
        peer that ANSWERS but has not caught up (e.g. mid-snapshot after
        a shard burst) withholds its ack yet stays in the quorum
        denominator — durability must not silently shrink because a
        replica is slow."""
        cfg = ReplicationConfig(lease_timeout=60, lease_interval=30)
        a, b, eps, _ = _pair(cfg=cfg)
        try:
            put_data_into_kvstore(eps, None, "sc", "k", b"v", timeout=10)
            coord = a.replication
            peer = coord.peers[0]
            with coord._lock:
                peer.fail_streak = 0           # clear startup noise
                peer.suspect = False
            orig = coord._sync_peer
            coord._sync_peer = lambda *args, **kw: False   # answers, lags
            try:
                for _ in range(5):
                    assert coord._replicate(coord.status()["seq"]) == 0
                assert not peer.suspect and peer.fail_streak == 0
                def _boom(*args, **kw):
                    raise ConnectionError("link down")
                coord._sync_peer = _boom       # transport failures count
                for _ in range(3):
                    coord._replicate(coord.status()["seq"])
                assert peer.suspect
            finally:
                coord._sync_peer = orig
        finally:
            a.stop()
            b.stop()

    def test_elastic_init_clears_replicate_to_standby(self):
        """New world ⇒ cleared coordinator — on EVERY replica. The
        init-time clears ride the journaled write path, so a worker GET
        against a read-serving standby can never fetch the previous
        world's coordinator address."""
        from horovod_tpu.elastic.rendezvous import ElasticRendezvousServer
        p1, p2 = find_free_port(), find_free_port()
        a = ElasticRendezvousServer(("127.0.0.1", p1))
        b = KVStoreServer(("127.0.0.1", p2))
        a.start()
        b.start()
        reps = [f"127.0.0.1:{p1}", f"127.0.0.1:{p2}"]
        a.enable_replication(reps[0], reps, role="primary",
                             config=ReplicationConfig(**FAST))
        b.enable_replication(reps[1], reps, role="standby",
                             config=ReplicationConfig(**FAST))
        eps = Endpoints([("127.0.0.1", p1), ("127.0.0.1", p2)],
                        reset_delay=0.1)
        try:
            put_data_into_kvstore(eps, None, "coordinator", "addr",
                                  b"old:1", timeout=10)
            put_data_into_kvstore(eps, None, "worker_addresses", "0",
                                  b"w0:1", timeout=10)
            assert b.snapshot()["coordinator"]["addr"] == b"old:1"
            a.init([])                         # new world, no seed yet
            # client_write acks only after the standby applied: the
            # standby's view is already clean, no wait loop needed
            assert not b.snapshot().get("coordinator")
            assert not b.snapshot().get("worker_addresses")
            a.init([], coordinator_addr="new:2")
            assert b.snapshot().get("coordinator", {}).get("addr") \
                == b"new:2"
        finally:
            a.stop()
            b.stop()


# ---------------------------------------------------------------------------
# Chaos: a real SIGKILL of the primary, three critical windows + the
# end-to-end elastic acceptance run.
# ---------------------------------------------------------------------------

_PRIMARY_SCRIPT = """
import sys, time
from horovod_tpu.runner.http_server import KVStoreServer
from horovod_tpu.runner.replication import ReplicationConfig
port, peer = int(sys.argv[1]), int(sys.argv[2])
reps = [f"127.0.0.1:{port}", f"127.0.0.1:{peer}"]
s = KVStoreServer(("127.0.0.1", port))
s.enable_replication(reps[0], reps, role="primary",
                     config=ReplicationConfig(lease_timeout=float(sys.argv[3]),
                                              lease_interval=float(sys.argv[4])))
s.start()
print("READY", flush=True)
while True:
    time.sleep(1)
"""


class _KilledPrimary:
    """A real subprocess primary + in-process standby, for SIGKILL chaos."""

    def __init__(self, tmp_path, lease_timeout=0.3, lease_interval=0.1,
                 primary_faults=None):
        self.p1, self.p2 = find_free_port(), find_free_port()
        self.reps = [f"127.0.0.1:{self.p1}", f"127.0.0.1:{self.p2}"]
        # bind the standby's port now, but DON'T start its lease clock
        # until the subprocess primary is actually serving — the primary
        # pays a multi-second interpreter/jax import before READY, and a
        # ticking lease would promote the standby before the primary's
        # first heartbeat (an inverted scenario: the test must kill a
        # live PRIMARY, not race a bootstrapping one)
        self.standby = KVStoreServer(("127.0.0.1", self.p2))
        self.standby.start()
        script = tmp_path / "primary.py"
        script.write_text(_PRIMARY_SCRIPT)
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=REPO_ROOT + os.pathsep +
                   os.environ.get("PYTHONPATH", ""))
        env.pop("HOROVOD_TPU_FAULTS", None)
        if primary_faults:
            # armed in the SUBPROCESS only (e.g. a per-PUT delay that
            # stretches an upload across the kill window); this process
            # stays fault-free
            env["HOROVOD_TPU_FAULTS"] = primary_faults
        self.proc = subprocess.Popen(
            [sys.executable, str(script), str(self.p1), str(self.p2),
             str(lease_timeout), str(lease_interval)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            cwd=REPO_ROOT, env=env, text=True)
        line = self.proc.stdout.readline()
        assert "READY" in line, f"primary subprocess never came up: {line!r}"
        self.standby.enable_replication(
            self.reps[1], self.reps, role="standby",
            config=ReplicationConfig(lease_timeout=lease_timeout,
                                     lease_interval=lease_interval))
        self.endpoints = Endpoints([("127.0.0.1", self.p1),
                                    ("127.0.0.1", self.p2)],
                                   trip_failures=3, reset_delay=0.1)

    def sigkill_primary(self):
        os.kill(self.proc.pid, signal.SIGKILL)
        self.proc.wait(timeout=10)

    def close(self):
        if self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(timeout=10)
        self.standby.stop()

    def assert_promoted_clean(self, timeout=10.0):
        """The acked-write-loss proof shared by every kill test: the
        standby promoted (waiting out the staggered lease grace), and its
        journal replay shows contiguous sequences — nothing acked fell
        into a gap."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline and \
                self.standby.replication.status()["role"] != "primary":
            time.sleep(0.05)
        st = self.standby.replication.status()
        assert st["role"] == "primary", st
        audit = self.standby.replication.audit_journal()
        assert audit["gaps"] == [], audit


@pytest.mark.chaos
class TestPrimaryKillChaos:
    def test_sigkill_mid_elastic_registration(self, tmp_path):
        """(a) the elastic registration write set keeps landing across a
        root SIGKILL: every ACKED registration survives on the promoted
        standby (journal-audited), and the worker notification manager's
        re-registration path works against the endpoint set afterwards."""
        cp = _KilledPrimary(tmp_path)
        reg = registry()
        fo_before = reg.counter("hvd_tpu_kv_failover_total").total()
        acked = {}
        try:
            for rank in range(8):
                if rank == 3:
                    cp.sigkill_primary()       # mid-sequence root kill
                key, val = str(rank), f"host{rank}:90{rank}".encode()
                put_data_into_kvstore(cp.endpoints, None,
                                      "worker_addresses", key, val,
                                      timeout=20)
                acked[key] = val               # acked -> must survive
            cp.assert_promoted_clean()
            final = cp.standby.snapshot()["worker_addresses"]
            for key, val in acked.items():
                assert final[key] == val, f"acked registration {key} lost"
            assert reg.counter("hvd_tpu_kv_failover_total").total() \
                > fo_before
            # the elastic manager's reregister path rides the same set
            from horovod_tpu.elastic.worker import WorkerNotificationManager
            mgr = WorkerNotificationManager()
            mgr.init(rendezvous_addr=cp.endpoints, rendezvous_port=None,
                     rank=0, hostname="hostA")
            try:
                mgr.reregister(rank=9)
                assert "9" in cp.standby.snapshot()["worker_addresses"]
            finally:
                mgr.shutdown()
        finally:
            cp.close()

    def test_sigkill_mid_checkpoint_shard_upload(self, tmp_path):
        """(b) a chunked checkpoint-shard upload started against the
        primary completes through the promoted standby, checksum-intact
        (put_large_value writes the meta LAST, so the reader's sha256
        proves every chunk survived the failover)."""
        # every PUT on the primary pays 30ms, so the 10-chunk upload
        # spans ~300ms and the kill lands mid-transfer deterministically
        cp = _KilledPrimary(tmp_path,
                            primary_faults="kv.server.put=*delay(30ms)")
        value = os.urandom(300_000)            # 10 chunks of 32 KiB
        box = {}

        def _upload():
            try:
                put_large_value(cp.endpoints, None, "ckptshard", "g5.r0",
                                value, chunk_bytes=32768, timeout=40)
                box["done"] = True
            except Exception as e:             # surfaced by the assert below
                box["err"] = e

        t = threading.Thread(target=_upload)
        try:
            t.start()
            time.sleep(0.15)                   # a few chunks in flight
            cp.sigkill_primary()
            t.join(timeout=60)
            assert box.get("done"), f"upload failed: {box.get('err')}"
            got = read_large_value(cp.endpoints, None, "ckptshard",
                                   "g5.r0", timeout=30)
            assert got == value
            cp.assert_promoted_clean()
        finally:
            cp.close()

    def test_sigkill_mid_long_poll(self, tmp_path):
        """(c) a long-poll GET in flight when the root dies keeps polling
        across the failover and completes when the (post-promotion) write
        lands — the reader never sees the kill."""
        cp = _KilledPrimary(tmp_path)
        got = {}

        def _reader():
            try:
                got["v"] = read_data_from_kvstore(
                    cp.endpoints, None, "rendezvous", "late", timeout=30,
                    poll_interval=0.05)
            except Exception as e:
                got["err"] = e

        t = threading.Thread(target=_reader)
        try:
            t.start()
            time.sleep(0.2)                    # reader is mid-long-poll
            cp.sigkill_primary()
            put_data_into_kvstore(cp.endpoints, None, "rendezvous",
                                  "late", b"after-failover", timeout=20)
            t.join(timeout=30)
            assert got.get("v") == b"after-failover", got
            cp.assert_promoted_clean()
        finally:
            cp.close()

    def test_elastic_run_survives_root_kill(self, tmp_path, monkeypatch):
        """The acceptance proof: an elastic training run whose telemetry
        rides a 1-primary/1-standby control plane (HOROVOD_KV_ENDPOINTS)
        survives a SIGKILL of the primary mid-run — automatic promotion,
        the run completes with NO restore/fleet restart, no acked-write
        loss (journal audit), and the shed/failover counters are visible
        in the standby's Prometheus scrape."""
        import urllib.request
        cp = _KilledPrimary(tmp_path)
        monkeypatch.setenv("HOROVOD_KV_ENDPOINTS",
                           ",".join(cp.reps))
        monkeypatch.setenv("HOROVOD_TPU_METRICS_INTERVAL", "0.2")
        reg = registry()
        hvd.shutdown()
        hvd.init()
        restores = {"n": 0}

        class _State(hvd.elastic.ObjectState):
            def restore(self):
                restores["n"] += 1
                super().restore()

        try:
            state = _State(batch=0)
            target = 6

            @hvd.elastic.run
            def train(state):
                while state.batch < target:
                    if state.batch == 2:
                        cp.sigkill_primary()   # root dies mid-run
                    out = np.asarray(hvd.allreduce(
                        np.ones(2, np.float32),
                        name=f"cp.b{state.batch}", op=hvd.Sum))
                    assert out[0] == hvd.size()
                    state.batch += 1
                    state.commit()
                    time.sleep(0.05)
                return state.batch

            assert train(state) == target
            assert restores["n"] == 0, "control-plane death restarted " \
                                       "the fleet"
            cp.assert_promoted_clean()
            # a deterministic post-failover publish (its own sweep fails
            # over past the dead primary), then the scrape from the
            # SURVIVING replica must carry the failover counters
            publish_snapshot((cp.endpoints, None), hvd.rank(),
                             reg.snapshot())
            assert reg.counter("hvd_tpu_kv_failover_total").total() > 0
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{cp.p2}/metrics/",
                    timeout=10) as resp:
                scrape = resp.read().decode()
            assert "hvd_tpu_kv_failover_total" in scrape
            assert "hvd_tpu_kv_promotions_total" in scrape
            # chaos scripts can still arm faults through the survivor
            put_data_into_kvstore(cp.endpoints, None, "faults", "spec",
                                  b"test.cp_post=1*noop()", timeout=10)
            assert faults.arm_from_kv(cp.endpoints, timeout=10) is True
        finally:
            faults.disarm()
            hvd.shutdown()
            cp.close()
