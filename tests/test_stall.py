"""Stall inspector tests (reference: test/test_stall.py + the coordinator's
'which ranks are missing which tensors' attribution, stall_inspector.h:70-92).
"""

import logging
import time

import pytest

from horovod_tpu.runner.http_server import KVStoreServer
from horovod_tpu.stall_inspector import StallInspector


@pytest.fixture
def kv_server():
    server = KVStoreServer(("127.0.0.1", 0))
    server.start()
    yield ("127.0.0.1", server.port)
    server.stop()


def test_local_stall_warning(caplog):
    insp = StallInspector(warning_seconds=0.2, check_interval=0.1)
    with caplog.at_level(logging.WARNING, logger="horovod_tpu"):
        insp.record_enqueue("grad.7")
        time.sleep(0.8)
    insp.stop()
    assert any("grad.7" in r.message for r in caplog.records)
    assert insp.stalled_tensors()


def test_done_clears_outstanding():
    insp = StallInspector(warning_seconds=10, check_interval=0.1)
    insp.record_enqueue("x")
    insp.record_done("x")
    assert insp.stalled_tensors() == []
    insp.stop()


def test_cross_rank_missing_tensor_attribution(kv_server, caplog):
    """Rank 1 submits a tensor rank 0 never does: rank 0's aggregation names
    both the tensor and the missing rank."""
    addr, port = kv_server
    r0 = StallInspector(warning_seconds=0.3, check_interval=0.15,
                        kv=(addr, port), rank=0, size=2)
    r1 = StallInspector(warning_seconds=0.3, check_interval=0.15,
                        kv=(addr, port), rank=1, size=2)
    with caplog.at_level(logging.WARNING, logger="horovod_tpu"):
        r1.record_enqueue("grads.bad")
        time.sleep(1.2)
    r0.stop()
    r1.stop()
    msgs = [r.message for r in caplog.records]
    assert any("grads.bad" in m and "missing on ranks [0]" in m
               for m in msgs), msgs


def test_publish_failure_escalates_to_warning(caplog):
    """ISSUE 3 satellite: KV publish failures were swallowed at debug level;
    after PUBLISH_FAIL_WARN_AFTER consecutive failures the inspector must
    emit a WARNING (with backoff — far fewer warnings than failures) and
    count into the registry's hvd_tpu_stall_publish_failures_total."""
    from horovod_tpu.metrics import registry
    from horovod_tpu.runner.http_server import find_free_port
    ctr = registry().counter("hvd_tpu_stall_publish_failures_total")
    before = ctr.total()
    # a freshly-probed free port with no listener: every publish fails fast
    insp = StallInspector(warning_seconds=0.1, check_interval=0.05,
                          kv=("127.0.0.1", find_free_port()), rank=1, size=2)
    with caplog.at_level(logging.DEBUG, logger="horovod_tpu"):
        time.sleep(1.2)
    insp.stop()
    failures = ctr.total() - before
    assert failures >= 3, failures
    warns = [r for r in caplog.records
             if r.levelno == logging.WARNING
             and "attribution is blind" in r.getMessage()]
    assert warns, "no escalation warning"
    # backoff: warnings fire at streaks 3, 6, 12, ... — not per tick
    assert len(warns) < failures / 2 + 1, (len(warns), failures)


def test_cross_rank_heartbeat_attribution(kv_server, caplog):
    """Rank 1's step heartbeat stops advancing while rank 0's continues:
    rank 0 reports the hung rank (SPMD-path coverage)."""
    addr, port = kv_server
    r0 = StallInspector(warning_seconds=0.4, check_interval=0.15,
                        kv=(addr, port), rank=0, size=2)
    r1 = StallInspector(warning_seconds=0.4, check_interval=0.15,
                        kv=(addr, port), rank=1, size=2)
    with caplog.at_level(logging.WARNING, logger="horovod_tpu"):
        r1.record_heartbeat(5)          # advances once, then goes silent
        deadline = time.time() + 2.5
        while time.time() < deadline:
            r0.record_heartbeat()       # keeps advancing
            time.sleep(0.1)
    r0.stop()
    r1.stop()
    msgs = [r.message for r in caplog.records]
    assert any("Rank 1" in m and "jitted step" in m for m in msgs), msgs
