"""Regression tests for the true violations the ISSUE 15 errflow sweep
found and fixed (the PR 7/11 bar: each test fails against the pre-fix
code — verified by swapping the HEAD implementation back in).

1. ``Engine.stop()`` set a flag and returned while the cycle thread
   slept out its full cycle time — an elastic teardown left a zombie
   cycle loop retiring handles while the next world's engine spun up.
   Now the loop is Event-paced and ``stop()`` joins it.
2. ``ShardBatchIterator.__iter__`` abandoned its loader thread on exit:
   the ``finally`` drained the queue but never joined, so an elastic
   reset (or a plain ``break``) left a loader reading shards against
   the next world's epoch. Now the finally drains AND joins.
3. ``find_free_port`` leaked its probe socket when ``bind`` raised
   (exhausted ephemeral range, EPERM sandboxes): ``close()`` sat on the
   success path only. Now ``try/finally``.
4. ``TaskService.stop()`` shut the HTTP server down but never joined
   the serve thread. Now it joins (asserted via the public stop path).
"""

import os
import threading
import time

import numpy as np
import pytest

pytestmark = pytest.mark.lint


def _threads_named(name):
    return [t for t in threading.enumerate() if t.name == name]


class TestEngineCycleThreadJoin:
    def test_stop_joins_cycle_loop(self, monkeypatch):
        """Pre-fix: stop() only flipped a flag read AFTER a
        time.sleep(cycle_time) — with a 2 s cycle the thread was still
        alive (sleeping) when stop() returned, deterministically. Post-
        fix: the Event wait is woken and the thread joined before
        stop() returns."""
        # a long cycle makes the pre-fix zombie window deterministic;
        # the Event-paced fix wakes immediately, so the test stays fast
        monkeypatch.setenv("HOROVOD_CYCLE_TIME", "2000")
        import horovod_tpu as hvd
        hvd.init()
        try:
            from horovod_tpu.core.state import global_state
            eng = global_state().engine
            assert eng is not None
            cycle = eng._cycle_thread
            assert cycle.is_alive()
            t0 = time.monotonic()
            eng.stop()
            assert not cycle.is_alive(), (
                "Engine.stop() returned with the cycle thread still "
                "running — the pre-fix zombie")
            # and it must not have waited out the 2 s sleep to do it
            assert time.monotonic() - t0 < 1.5
        finally:
            hvd.shutdown()


class TestDataLoaderJoin:
    def test_abandoned_iterator_joins_loader(self, tmp_path):
        """Pre-fix: closing the iterator drained the queue and returned
        with the loader thread still loading the next shard — a zombie
        'hvd-data-loader' survived the iterator. Post-fix: the finally
        joins it."""
        from horovod_tpu.data import ShardBatchIterator
        paths = []
        for i in range(6):
            p = tmp_path / f"shard{i}.npz"
            np.savez(p, x=np.zeros((64, 4), np.float32),
                     y=np.zeros((64,), np.int32))
            paths.append(str(p))
        ds = ShardBatchIterator(paths, batch_size=8, shuffle=False,
                                prefetch=1)
        it = iter(ds)
        next(it)               # loader is now racing ahead of the consumer
        it.close()             # abandon mid-stream (elastic reset / break)
        leftovers = _threads_named("hvd-data-loader")
        assert not any(t.is_alive() for t in leftovers), (
            "iterator close() left a live loader thread — the pre-fix "
            "zombie")


class TestFindFreePortSocketLifecycle:
    def test_socket_closed_when_bind_raises(self, monkeypatch):
        """Pre-fix: close() ran after bind/getsockname on the straight
        line, so a bind failure leaked the probe socket. Post-fix: the
        finally closes it on the exception edge too."""
        from horovod_tpu.runner import http_server

        closed = []

        class _BoomSocket:
            def __init__(self, *a, **k):
                pass

            def bind(self, addr):
                raise OSError("injected bind failure")

            def getsockname(self):  # pragma: no cover — bind raises first
                return ("", 0)

            def close(self):
                closed.append(True)

        monkeypatch.setattr(http_server.socket, "socket", _BoomSocket)
        with pytest.raises(OSError, match="injected bind failure"):
            http_server.find_free_port()
        assert closed, (
            "find_free_port leaked its socket on the bind-failure edge "
            "— the pre-fix leak")

    def test_still_returns_a_port(self):
        port = __import__(
            "horovod_tpu.runner.http_server",
            fromlist=["find_free_port"]).find_free_port()
        assert 0 < port < 65536


class TestTaskServiceThreadJoin:
    def test_stop_joins_serve_thread(self):
        from horovod_tpu.runner.service import TaskService
        svc = TaskService(key=b"secret", addr=("127.0.0.1", 0))
        svc.start()
        thread = svc._thread
        assert thread is not None and thread.is_alive()
        svc.stop()
        assert not thread.is_alive()
        assert svc._thread is None


@pytest.mark.skipif(os.environ.get("HOROVOD_SKIP_SLOW") == "1",
                    reason="explicitly skipped")
class TestLoaderJoinBoundsShutdown:
    def test_join_is_bounded(self, tmp_path):
        """The drain+join loop is deadline-bounded: even a loader mid-
        np.load exits promptly once the queue drains (no unbounded
        shutdown hang was introduced by the fix)."""
        from horovod_tpu.data import ShardBatchIterator
        p = tmp_path / "one.npz"
        np.savez(p, x=np.zeros((1024, 8), np.float32),
                 y=np.zeros((1024,), np.int32))
        ds = ShardBatchIterator([str(p)] * 4, batch_size=16,
                                shuffle=False, prefetch=1)
        it = iter(ds)
        next(it)
        t0 = time.monotonic()
        it.close()
        assert time.monotonic() - t0 < 5.5
