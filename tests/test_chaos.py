"""Chaos suite (ISSUE 4): every failure path exercised deterministically
through the failpoint subsystem — no real process is ever killed.

Covers the acceptance criteria:
- a one-shot hang in collective dispatch is converted by the collective
  watchdog into ``HorovodInternalError`` and the elastic run-loop recovers
  end-to-end (restore -> reset -> finish at the target step);
- a transient KV outage (first 3 PUTs fail) loses no stall/metrics/
  registration writes: retry counters advance and the final KV state is
  byte-identical to a no-fault run;
- the long-poll read survives a hung server connection (capped per-request
  timeout satellite);
- ``reregister`` retries and escalates loudly (satellite);
- malformed hosts-updated notifications are rejected loudly (satellite);
- the elastic run-loop's bounded-retry escalation, failpoint-driven
  (satellite).
"""

import json
import logging
import os
import threading
import time
import urllib.error

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu import faults
from horovod_tpu.metrics import publish_snapshot, registry
from horovod_tpu.runner.http_client import (put_data_into_kvstore,
                                            read_data_from_kvstore)
from horovod_tpu.runner.http_server import KVStoreServer

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _disarm():
    faults.disarm()
    yield
    faults.disarm()


@pytest.fixture
def kv_server():
    server = KVStoreServer(("127.0.0.1", 0))
    server.start()
    yield server
    faults.disarm()   # release any parked server-side hangs first
    server.stop()


def _kv_state(server) -> dict:
    # the public consistent-copy surface (ISSUE 12 satellite) — tests no
    # longer reach into server._lock/_store privates
    return server.snapshot()


# ---------------------------------------------------------------------------
# Acceptance: watchdog converts a one-shot collective hang into end-to-end
# elastic recovery — deterministically, without killing any process.
# ---------------------------------------------------------------------------

class _CountingState(hvd.elastic.ObjectState):
    def __init__(self, **kwargs):
        self.restores = 0
        super().__init__(**kwargs)

    def restore(self):
        self.restores += 1
        super().restore()


def test_watchdog_hang_recovery_end_to_end(monkeypatch, tmp_path):
    """A peer's collective stops completing (modeled by a one-shot hang at
    the dispatch edge, where the op already sits in the stall inspector's
    outstanding table). The watchdog must fire within
    HOROVOD_TPU_COLLECTIVE_DEADLINE, surface HorovodInternalError, and the
    elastic run-loop must restore the last commit and finish training.
    The escalation must also dump the flight-recorder trace ring (ISSUE 5)
    BEFORE poisoning the engine, so the hang post-mortem has the spans
    that led into it."""
    deadline = 1.0
    monkeypatch.setenv("HOROVOD_TPU_COLLECTIVE_DEADLINE", str(deadline))
    monkeypatch.setenv("HOROVOD_TPU_TRACE_DUMP_DIR", str(tmp_path))
    monkeypatch.delenv("HOROVOD_STALL_CHECK_DISABLE", raising=False)
    hvd.shutdown()
    hvd.init()
    reg = registry()
    esc_before = reg.counter("hvd_tpu_watchdog_escalations_total").total()
    rec_before = reg.counter("hvd_tpu_elastic_recoveries_total").value(
        kind="internal")
    try:
        faults.arm("engine.dispatch=hang()")
        state = _CountingState(batch=0)
        target = 5

        @hvd.elastic.run
        def train(state):
            while state.batch < target:
                out = np.asarray(hvd.allreduce(
                    np.ones(2, np.float32), name=f"chaos.b{state.batch}",
                    op=hvd.Sum))
                assert out[0] == hvd.size()
                state.batch += 1
                state.commit()
            return state.batch

        t0 = time.monotonic()
        assert train(state) == target
        elapsed = time.monotonic() - t0
        # the hang fired (one-shot) and the watchdog broke it: the whole
        # recovery must take the deadline plus modest overhead, not the
        # legacy forever
        assert elapsed < deadline + 15, elapsed
        assert state.restores == 1, "run-loop never restored committed state"
        assert state.batch == target
        assert reg.counter("hvd_tpu_watchdog_escalations_total").total() \
            == esc_before + 1
        assert reg.counter("hvd_tpu_elastic_recoveries_total").value(
            kind="internal") == rec_before + 1
        assert faults.hits("engine.dispatch") == 1
        # flight recorder (ISSUE 5 acceptance): the escalation dumped the
        # in-memory trace ring to disk, and the dump holds the spans that
        # led into the hang — including the wedged op, sealed open.
        dump = tmp_path / f"hvd_tpu_flight_rank{hvd.rank()}.json"
        assert dump.exists(), "watchdog escalation wrote no flight dump"
        with open(dump) as f:
            flight = json.load(f)
        assert flight["otherData"]["flight_recorder"] is True
        spans = [e for e in flight["traceEvents"] if e.get("ph") == "B"]
        assert any(e["args"].get("tensor", "").startswith("chaos.b")
                   for e in spans), spans
    finally:
        faults.disarm()
        hvd.shutdown()


def test_watchdog_peer_heartbeat_escalation(kv_server):
    """SPMD-path watchdog leg: rank 1's step heartbeat freezes while rank
    0's keeps advancing — rank 0 must escalate (HorovodInternalError to the
    hook + counter) within the deadline, not merely warn."""
    from horovod_tpu.common.exceptions import HorovodInternalError
    from horovod_tpu.stall_inspector import StallInspector
    addr, port = "127.0.0.1", kv_server.port
    reg = registry()
    esc_before = reg.counter("hvd_tpu_watchdog_escalations_total").total()
    escalations = []
    r0 = StallInspector(warning_seconds=30, check_interval=0.1,
                        kv=(addr, port), rank=0, size=2,
                        collective_deadline=0.5,
                        escalate=escalations.append)
    r1 = StallInspector(warning_seconds=30, check_interval=0.1,
                        kv=(addr, port), rank=1, size=2,
                        collective_deadline=0.5)
    try:
        r1.record_heartbeat(5)            # advances once, then freezes
        deadline = time.monotonic() + 6.0
        while time.monotonic() < deadline and not escalations:
            r0.record_heartbeat()         # rank 0 keeps stepping
            time.sleep(0.05)
        assert escalations, "rank 0 watchdog never escalated"
        assert isinstance(escalations[0], HorovodInternalError)
        assert "rank 1" in str(escalations[0])
        assert reg.counter("hvd_tpu_watchdog_escalations_total").total() \
            > esc_before
    finally:
        r0.stop()
        r1.stop()


def test_watchdog_skips_idle_joined_peer(kv_server):
    """A rank parked in hvd.join() (uneven data) publishes hb_idle: the
    peer leg must NOT escalate over its legitimately frozen heartbeat."""
    from horovod_tpu.stall_inspector import StallInspector
    addr, port = "127.0.0.1", kv_server.port
    escalations = []
    r0 = StallInspector(warning_seconds=30, check_interval=0.1,
                        kv=(addr, port), rank=0, size=2,
                        collective_deadline=0.4,
                        escalate=escalations.append)
    r1 = StallInspector(warning_seconds=30, check_interval=0.1,
                        kv=(addr, port), rank=1, size=2,
                        collective_deadline=0.4)
    try:
        r1.record_heartbeat(5)
        r1.set_heartbeat_idle(True)       # what engine.join() wires
        deadline = time.monotonic() + 2.5
        while time.monotonic() < deadline:
            r0.record_heartbeat()
            time.sleep(0.05)
        assert not escalations, escalations
        # ...and leaving join() re-enables the check
        r1.set_heartbeat_idle(False)
        deadline = time.monotonic() + 6.0
        while time.monotonic() < deadline and not escalations:
            r0.record_heartbeat()
            time.sleep(0.05)
        assert escalations, "escalation never resumed after idle cleared"
    finally:
        r0.stop()
        r1.stop()


def test_arm_from_kv(kv_server):
    """The one-place-arms-every-worker path: spec present arms, absent
    warns+returns False, a bad spec raises (never silently unarmed)."""
    addr, port = "127.0.0.1", kv_server.port
    assert faults.arm_from_kv(addr, port, timeout=0.5) is False
    assert not faults.enabled()
    put_data_into_kvstore(addr, port, "faults", "spec",
                          b"test.kvarm=2*noop()", timeout=5)
    assert faults.arm_from_kv(addr, port, timeout=5) is True
    assert faults.enabled()
    faults.failpoint("test.kvarm")
    assert faults.hits("test.kvarm") == 1
    faults.disarm()
    put_data_into_kvstore(addr, port, "faults", "spec",
                          b"not a valid spec", timeout=5)
    with pytest.raises(ValueError):
        faults.arm_from_kv(addr, port, timeout=5)


def test_break_hangs_does_not_latch():
    """After one break, a LATER hang() in the same armed spec must park
    again (multi-round chaos), not instantly re-raise the stale error."""
    from horovod_tpu.common.exceptions import HorovodInternalError
    faults.arm("test.latch=2*hang()")
    box = {}

    def _blocked(slot):
        try:
            faults.failpoint("test.latch")
            box[slot] = "resumed"
        except Exception as e:
            box[slot] = e

    t1 = threading.Thread(target=_blocked, args=("first",), daemon=True)
    t1.start()
    time.sleep(0.1)
    faults.break_hangs(HorovodInternalError("round 1"))
    t1.join(timeout=5)
    assert isinstance(box["first"], HorovodInternalError)
    # second hang must PARK, not inherit the stale break
    t2 = threading.Thread(target=_blocked, args=("second",), daemon=True)
    t2.start()
    time.sleep(0.2)
    assert t2.is_alive(), "second hang inherited the stale break"
    faults.break_hangs(None)              # released without error
    t2.join(timeout=5)
    assert box["second"] == "resumed"


def test_poisoned_engine_raises_instead_of_hanging():
    """After a watchdog escalation the engine must refuse every later
    submission with the same HorovodInternalError instead of queueing
    behind the wedged collective."""
    from horovod_tpu.common.exceptions import HorovodInternalError
    hvd.shutdown()
    hvd.init()
    try:
        eng = hvd.global_state().engine
        eng.poison(HorovodInternalError("watchdog: test"))
        with pytest.raises(HorovodInternalError):
            hvd.allreduce(np.ones(2, np.float32), name="poisoned.a")
        with pytest.raises(HorovodInternalError):
            hvd.barrier()
    finally:
        hvd.shutdown()


def test_sharded_prefetch_survives_elastic_restore():
    """ISSUE 6 acceptance: the ZeRO-1 all-gather prefetch leg rides the
    chaos suite's elastic restore. A one-shot injected prefetch-launch
    failure surfaces as HorovodInternalError, the elastic run-loop
    restores the last commit and re-enters training, and the prefetch
    subsystem is still live afterwards (legs keep launching) — the
    failure invalidated nothing it shouldn't and poisoned nothing."""
    import jax
    import jax.numpy as jnp
    import optax
    from horovod_tpu.optimizer import DistributedEagerOptimizer
    hvd.shutdown()
    # legs ride the staged schedule — force it via env, not just the live
    # config: the elastic restore rebuilds the engine (fresh Config from
    # env), and the rebuilt engine must keep launching legs
    os.environ["HOROVOD_TPU_OVERLAP_PIPELINE"] = "staged"
    hvd.init()
    eng = hvd.global_state().engine
    reg = registry()
    rec_before = reg.counter("hvd_tpu_elastic_recoveries_total").value(
        kind="internal")
    legs_before = reg.counter("hvd_tpu_overlap_prefetch_total").total()
    try:
        eng.config.zero1_prefetch = True
        faults.arm("overlap.prefetch=1*raise(HorovodInternalError)")
        opt = DistributedEagerOptimizer(optax.sgd(0.05), sharded=True)
        box = {"params": {"w": jnp.ones((4, 4))}}
        box["opt"] = opt.init(box["params"])
        grad_fn = jax.jit(jax.grad(lambda p: jnp.sum(p["w"] ** 2)))
        state = _CountingState(batch=0)
        target = 4

        @hvd.elastic.run
        def train(state):
            while state.batch < target:
                g = grad_fn(box["params"])
                box["params"], box["opt"] = opt.update_and_apply(
                    g, box["opt"], box["params"])
                state.batch += 1
                state.commit()
            return state.batch

        assert train(state) == target
        jax.block_until_ready(box["params"]["w"])
        assert state.restores == 1, \
            "run-loop never restored committed state"
        assert faults.hits("overlap.prefetch") == 1
        assert reg.counter("hvd_tpu_elastic_recoveries_total").value(
            kind="internal") == rec_before + 1
        # the prefetch subsystem kept launching legs after the restore
        assert reg.counter("hvd_tpu_overlap_prefetch_total").total() \
            > legs_before
        assert bool(np.isfinite(np.asarray(box["params"]["w"])).all())
    finally:
        faults.disarm()
        os.environ.pop("HOROVOD_TPU_OVERLAP_PIPELINE", None)
        hvd.shutdown()


def test_elastic_restore_writes_flight_dump(monkeypatch, tmp_path):
    """ISSUE 20 satellite: when HorovodInternalError hands control to
    the elastic restore path, a flight dump is written BEFORE the
    restore (through the rate-limited FlightDumper, trigger
    ``elastic_restore``) — even with the stall watchdog disabled, so
    the post-mortem tier does not depend on the escalation tier."""
    monkeypatch.setenv("HOROVOD_TPU_TRACE_DUMP_DIR", str(tmp_path))
    monkeypatch.setenv("HOROVOD_STALL_CHECK_DISABLE", "1")
    hvd.shutdown()
    hvd.init()
    reg = registry()
    dumps_before = reg.counter("hvd_tpu_flight_dumps_total").value(
        trigger="elastic_restore")
    try:
        faults.arm("engine.enqueue=1*raise(HorovodInternalError)")
        state = _CountingState(batch=0)
        target = 4

        @hvd.elastic.run
        def train(state):
            while state.batch < target:
                out = np.asarray(hvd.allreduce(
                    np.ones(2, np.float32),
                    name=f"erd.b{state.batch}.r{state.restores}",
                    op=hvd.Sum))
                assert out[0] == hvd.size()
                state.batch += 1
                state.commit()
            return state.batch

        assert train(state) == target
        assert state.restores == 1, "run-loop never restored"
        assert faults.hits("engine.enqueue") == 1
        dump = tmp_path / f"hvd_tpu_flight_rank{hvd.rank()}.json"
        assert dump.exists(), "elastic restore wrote no flight dump"
        with open(dump) as f:
            assert json.load(f)["otherData"]["flight_recorder"] is True
        assert reg.counter("hvd_tpu_flight_dumps_total").value(
            trigger="elastic_restore") == dumps_before + 1
    finally:
        faults.disarm()
        hvd.shutdown()


# ---------------------------------------------------------------------------
# Acceptance: transient KV outage loses no stall/metrics/registration
# writes — final KV state matches the no-fault run (two-rank write set).
# ---------------------------------------------------------------------------

def _exercise_kv_writes(addr: str, port: int):
    """The control-plane write set of a 2-rank world: stall liveness,
    metrics snapshots, and worker-address registrations for both ranks."""
    for rank in (0, 1):
        put_data_into_kvstore(
            addr, port, "stall", str(rank),
            json.dumps({"outstanding": [], "hb_step": 7,
                        "replay_fallbacks": 0}).encode(), timeout=10)
        publish_snapshot((addr, port), rank,
                         {"enabled": True, "counters": {}, "gauges": {},
                          "histograms": {}, "events": {}})
        put_data_into_kvstore(addr, port, "worker_addresses", str(rank),
                              f"host{rank}:90{rank}".encode(), timeout=10)


def test_kv_outage_loses_no_writes():
    reg = registry()
    a = KVStoreServer(("127.0.0.1", 0))
    b = KVStoreServer(("127.0.0.1", 0))
    a.start()
    b.start()
    try:
        _exercise_kv_writes("127.0.0.1", a.port)      # no-fault reference
        retries_before = reg.counter("hvd_tpu_kv_retries_total").total()
        faults.arm("kv.put=3*raise(ConnectionError)")  # transient outage
        _exercise_kv_writes("127.0.0.1", b.port)
        faults.disarm()
        assert faults.hits("kv.put") == 0  # disarmed resets accounting
        assert _kv_state(a) == _kv_state(b), \
            "KV state diverged: the outage lost writes"
        assert reg.counter("hvd_tpu_kv_retries_total").total() \
            >= retries_before + 3
    finally:
        faults.disarm()
        a.stop()
        b.stop()


def test_read_survives_hung_server_connection(kv_server):
    """Satellite: the long-poll GET used to pass its WHOLE deadline as the
    per-request socket timeout, so one hung connection consumed it all.
    With the cap, a hung connection costs one capped request and the retry
    reconnects."""
    addr, port = "127.0.0.1", kv_server.port
    put_data_into_kvstore(addr, port, "scope", "k", b"v42", timeout=5)
    faults.arm("kv.server.get=hang()")   # first connection wedges forever
    t0 = time.monotonic()
    out = read_data_from_kvstore(addr, port, "scope", "k", timeout=8.0,
                                 poll_interval=0.05,
                                 per_request_timeout=0.3)
    elapsed = time.monotonic() - t0
    assert out == b"v42"
    assert elapsed < 4.0, \
        f"hung connection consumed the deadline ({elapsed:.1f}s)"


def test_put_survives_hung_server_connection(kv_server):
    """The write path gets the same per-request cap as the read path: a
    server that accepts the PUT connection and wedges costs one capped
    attempt, and the retry loop lands the write within the deadline."""
    addr, port = "127.0.0.1", kv_server.port
    faults.arm("kv.server.put=hang()")   # first PUT connection wedges
    t0 = time.monotonic()
    put_data_into_kvstore(addr, port, "scope", "pk", b"pv", timeout=10,
                          per_request_timeout=0.3)
    elapsed = time.monotonic() - t0
    faults.disarm()
    assert elapsed < 4.0, \
        f"hung PUT connection consumed the deadline ({elapsed:.1f}s)"
    assert _kv_state(kv_server)["scope"]["pk"] == b"pv"


def test_reregister_retries_then_escalates_loudly(kv_server, caplog):
    """Satellite: a failed post-reset re-registration was swallowed at
    debug level. Transient failures must be retried to success; a
    permanent outage must WARN and count into the give-up counter."""
    from horovod_tpu.elastic.worker import WorkerNotificationManager
    addr, port = "127.0.0.1", kv_server.port
    reg = registry()
    mgr = WorkerNotificationManager()
    mgr.init(rendezvous_addr=addr, rendezvous_port=port, rank=0,
             hostname="hostA")
    try:
        # transient: two failures, then the KV heals — the write must land
        faults.arm("elastic.reregister=2*raise(ConnectionError)")
        mgr.reregister(rank=3)
        assert _kv_state(kv_server)["worker_addresses"]["3"] \
            == _kv_state(kv_server)["worker_addresses"]["0"]
        # permanent: every attempt fails — WARNING + give-up counter
        gave_before = reg.counter("hvd_tpu_kv_gave_up_total").value(
            op="reregister")
        faults.arm("elastic.reregister=*raise(ConnectionError)")
        with caplog.at_level(logging.WARNING, logger="horovod_tpu.elastic"):
            mgr.reregister(rank=4)
        assert any("re-registration" in r.message and
                   r.levelno == logging.WARNING for r in caplog.records)
        assert reg.counter("hvd_tpu_kv_gave_up_total").value(
            op="reregister") == gave_before + 1
        assert "4" not in _kv_state(kv_server).get("worker_addresses", {})
    finally:
        faults.disarm()
        mgr.shutdown()


def test_malformed_notify_rejected_loudly(caplog):
    """Satellite: a malformed hosts-updated payload used to 400 with no
    trace — an invisible lost membership event under driver/worker version
    skew. Now: WARNING + hvd_tpu_notify_rejects_total."""
    from horovod_tpu.elastic.worker import (WorkerNotificationManager,
                                            WorkerNotificationService)
    mgr = WorkerNotificationManager()
    svc = WorkerNotificationService(mgr)
    svc.start()
    reg = registry()
    before = reg.counter("hvd_tpu_notify_rejects_total").total()
    try:
        with caplog.at_level(logging.WARNING, logger="horovod_tpu.elastic"):
            with pytest.raises(urllib.error.HTTPError):
                put_data_into_kvstore("127.0.0.1", svc.port, "notify",
                                      "hosts_updated", b"not a payload",
                                      timeout=5, retries=0)
        assert reg.counter("hvd_tpu_notify_rejects_total").total() \
            == before + 1
        assert any("version skew" in r.message for r in caplog.records)
        # a well-formed payload still goes through to listeners
        got = []

        class _L:
            def on_hosts_updated(self, ts, res):
                got.append((ts, res))

        mgr.register_listener(_L())
        put_data_into_kvstore("127.0.0.1", svc.port, "notify",
                              "hosts_updated", b"123 1", timeout=5)
        assert got == [(123, 1)]
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# Satellite: elastic run-loop bounded-retry escalation, failpoint-driven
# (no subprocess kills).
# ---------------------------------------------------------------------------

class _FakeState:
    def __init__(self):
        self.restores = 0
        self.syncs = 0
        self._commit_count = 0

    def sync(self):
        self.syncs += 1

    def restore(self):
        self.restores += 1

    def on_reset(self):
        pass

    def commit(self):
        self._commit_count += 1


class TestRunLoopEscalationChaos:
    @pytest.fixture(autouse=True)
    def _no_rendezvous(self, monkeypatch):
        monkeypatch.delenv("HOROVOD_GLOO_RENDEZVOUS_ADDR", raising=False)

    def _budget(self):
        import importlib
        return importlib.import_module(
            "horovod_tpu.elastic.run")._MAX_RUNTIME_ERROR_RETRIES

    def test_consecutive_raw_failures_escalate(self):
        import jax
        import importlib
        run_fn = importlib.import_module("horovod_tpu.elastic.run").run_fn
        budget = self._budget()
        faults.arm("test.runloop=*raise(JaxRuntimeError)")
        state = _FakeState()
        attempts = []

        def train(s):
            attempts.append(1)
            faults.failpoint("test.runloop")
            return "unreachable"

        with pytest.raises(jax.errors.JaxRuntimeError):
            run_fn(train, lambda: None)(state)
        assert len(attempts) == budget + 1
        assert state.restores == budget

    def test_progress_resets_the_counter(self):
        import importlib
        run_fn = importlib.import_module("horovod_tpu.elastic.run").run_fn
        budget = self._budget()
        n_fail = budget * 2
        faults.arm(f"test.runloop={n_fail}*raise(JaxRuntimeError)")
        state = _FakeState()

        def train(s):
            s.commit()                      # progress before every failure
            faults.failpoint("test.runloop")
            return "done"

        assert run_fn(train, lambda: None)(state) == "done"
        assert state.restores == n_fail     # every failure recovered

    def test_internal_error_never_counts(self):
        reg = registry()
        import importlib
        run_fn = importlib.import_module("horovod_tpu.elastic.run").run_fn
        budget = self._budget()
        n_fail = budget * 3                 # far past the raw budget
        rec_before = reg.counter("hvd_tpu_elastic_recoveries_total").value(
            kind="internal")
        faults.arm(f"test.runloop={n_fail}*raise(HorovodInternalError)")
        state = _FakeState()

        def train(s):
            faults.failpoint("test.runloop")   # NO commits, all internal
            return "done"

        assert run_fn(train, lambda: None)(state) == "done"
        assert state.restores == n_fail
        assert reg.counter("hvd_tpu_elastic_recoveries_total").value(
            kind="internal") == rec_before + n_fail

    def test_internal_error_resets_raw_streak(self):
        """Interleaved raw/internal failures: each HorovodInternalError
        resets the consecutive-raw counter, so raw streaks below the budget
        never escalate even when the total is far past it."""
        import importlib
        run_fn = importlib.import_module("horovod_tpu.elastic.run").run_fn
        budget = self._budget()
        chain = "->".join(
            [f"{budget}*raise(JaxRuntimeError)", "raise(HorovodInternalError)"]
            * 3)
        faults.arm(f"test.runloop={chain}")
        state = _FakeState()

        def train(s):
            faults.failpoint("test.runloop")
            return "done"

        assert run_fn(train, lambda: None)(state) == "done"
        assert state.restores == (budget + 1) * 3


# ---------------------------------------------------------------------------
# ISSUE 9: durable-restore chaos — a world recovered from on-disk/peer
# shards with NO in-memory commit, to bitwise step parity. Kill-free.
# ---------------------------------------------------------------------------

class TestDurableRestoreChaos:
    TARGET = 8
    CRASH_AT = 5

    @staticmethod
    def _grad(step):
        return np.arange(11, dtype=np.float32) * (step + 1) * 0.01

    @classmethod
    def _train(cls, state, until, after_commit=None):
        """Deterministic committed training: every step allreduces a
        step-dependent gradient and commits (the durable tier snapshots
        asynchronously on each commit when a manager is wired).
        ``after_commit`` lets the chaos phase drain the async writer per
        step, making the write-failpoint accounting exact (the
        double-buffer would otherwise legally collapse bursts)."""
        while state.batch < until:
            g = np.asarray(hvd.allreduce(
                cls._grad(state.batch), name=f"dur.g{state.batch}",
                op=hvd.Sum))
            state.params = {"w": np.asarray(state.params["w"]) - g}
            state.batch += 1
            state.commit()
            if after_commit is not None:
                after_commit()
        return np.asarray(state.params["w"]).copy()

    def test_durable_restore_reaches_bitwise_step_parity(
            self, tmp_path, monkeypatch):
        """The end-to-end durable-restore proof (ISSUE 9 acceptance):

        1. an uninterrupted run establishes the reference params;
        2. a committing run with the durable tier on — and a TRANSIENT
           checkpoint.write fault injected (first two writes fail) —
           trains to the crash point and is then thrown away entirely:
           the state is explicitly reset (a FRESH TPUState, zero
           in-memory commits — the preempted-host case), one rank
           directory is deleted (lost disk), and checkpoint.restore is
           armed with a delay;
        3. the elastic run-loop restores the world from the surviving
           on-disk/peer shards and finishes training — bitwise equal to
           the uninterrupted reference."""
        import jax.numpy as jnp
        from horovod_tpu.core.state import global_state
        reg = registry()

        hvd.shutdown()
        hvd.init()
        init = {"w": jnp.zeros(11, jnp.float32)}

        # 1. uninterrupted reference (no durable tier)
        ref = self._train(hvd.elastic.TPUState(params=init, batch=0),
                          self.TARGET)

        # 2. committing run with the durable tier; first two writes fail
        #    transiently (counted, training unaffected)
        monkeypatch.setenv("HOROVOD_TPU_CHECKPOINT_DIR", str(tmp_path))
        hvd.shutdown()
        hvd.init()
        failed0 = reg.counter("hvd_tpu_ckpt_snapshots_total").value(
            outcome="failed")
        faults.arm("checkpoint.write=2*raise(OSError)")
        mgr = global_state().checkpoint_manager
        assert mgr is not None
        self._train(hvd.elastic.TPUState(params=init, batch=0),
                    self.CRASH_AT,
                    after_commit=lambda: mgr.wait_idle(30))
        assert mgr.wait_idle(60)
        faults.disarm()
        assert reg.counter("hvd_tpu_ckpt_snapshots_total").value(
            outcome="failed") == failed0 + 2
        # the transient write faults cost generations, not correctness:
        # the newest surviving generation is the crash-point commit
        assert mgr.latest_generation()[0] == self.CRASH_AT

        # simulate the host loss: the in-memory state is gone (fresh
        # TPUState below) AND the "other host's" disk is gone — here the
        # world is size 1, so instead corrupt nothing but prove the
        # restore edge is exercised via the armed failpoint delay
        faults.arm("checkpoint.restore=1*delay(50ms)")
        durable0 = reg.counter("hvd_tpu_elastic_recoveries_total").value(
            kind="durable")

        # 3. a FRESH state with zero in-memory commits, driven through
        #    the elastic run-loop to the target
        fresh = hvd.elastic.TPUState(params=init, batch=0)
        target = self.TARGET

        @hvd.elastic.run
        def continue_training(state):
            assert state.batch == self.CRASH_AT, \
                f"durable restore missed: batch={state.batch}"
            return self._train(state, target)

        got = continue_training(fresh)
        np.testing.assert_array_equal(got, ref)   # bitwise step parity
        assert reg.counter("hvd_tpu_elastic_recoveries_total").value(
            kind="durable") == durable0 + 1
        hvd.shutdown()
        monkeypatch.delenv("HOROVOD_TPU_CHECKPOINT_DIR")
        hvd.init()

    def test_peer_redundant_shard_drop_step_parity(self, tmp_path):
        """The multi-rank shard-drop leg (kill-free): an np=3 world's
        committed generation loses one rank's ENTIRE disk; a fresh
        TPUState wired to a fresh manager restores from the neighbor's
        replica and continues to bitwise step parity."""
        import shutil as _sh
        import jax.numpy as jnp
        from horovod_tpu.checkpoint import CheckpointManager
        from horovod_tpu.core.state import global_state

        hvd.shutdown()
        hvd.init()
        init = {"w": jnp.zeros(11, jnp.float32)}
        ref = self._train(hvd.elastic.TPUState(params=init, batch=0),
                          self.TARGET)

        # an np=3 world commits generations up to the crash point: the
        # same committed tree per rank, each writing only its byte shard
        # + its successor's replica (the TPUState payload layout)
        committed = self._train(
            hvd.elastic.TPUState(params=init, batch=0), self.CRASH_AT)
        mgrs = [CheckpointManager(str(tmp_path), rank=r, world_size=3,
                                  redundancy=1) for r in range(3)]
        try:
            for step in (self.CRASH_AT - 1, self.CRASH_AT):
                # two generations so GC/partial logic sees history
                w = committed if step == self.CRASH_AT else committed + 1
                for m in mgrs:
                    m.snapshot({"pytrees": {"params": {"w": w}}}, step,
                               extras={"batch": step})
                for m in mgrs:
                    assert m.wait_idle(60)
        finally:
            for m in mgrs:
                m.close(flush=False)

        _sh.rmtree(tmp_path / "rank2")          # lost host
        fresh = hvd.elastic.TPUState(params=init, batch=0)
        gs = global_state()
        assert gs.checkpoint_manager is None
        gs.checkpoint_manager = CheckpointManager(str(tmp_path), rank=0,
                                                  world_size=3,
                                                  redundancy=1)
        try:
            fresh.restore()                     # durable tier engages
            assert fresh.batch == self.CRASH_AT
            np.testing.assert_array_equal(
                np.asarray(fresh.params["w"]), committed)
            got = self._train(fresh, self.TARGET)
            np.testing.assert_array_equal(got, ref)   # bitwise parity
        finally:
            gs.checkpoint_manager.close(flush=False)
            gs.checkpoint_manager = None

# ---------------------------------------------------------------------------
# ISSUE 13 acceptance: a compressed step recovers through elastic restore
# with error-feedback residuals invalidated (never poisoned)
# ---------------------------------------------------------------------------


def test_compressed_step_recovers_through_elastic_restore():
    """An injected encode failure on a compressed step surfaces as
    HorovodInternalError, the elastic run-loop restores committed state
    and re-initializes (fresh engine — the pre-failure residual lineage
    is dropped with its world: invalidated, never poisoned), and training
    resumes COMPRESSED to the target with residuals repopulating; a later
    world-version bump invalidates the new lineage through the counted gc
    edge. The codec needs a >1 world view to engage, installed the
    heterogeneous-topology test's way (the in-process chaos world is one
    rank; multi-rank compressed parity lives in test_multiprocess)."""
    import dataclasses
    import jax
    import jax.numpy as jnp
    from horovod_tpu.core.state import global_state
    from horovod_tpu.metrics import counter_total, snapshot
    hvd.shutdown()
    os.environ["HOROVOD_TPU_COMPRESSION"] = "int8"
    hvd.init()

    def ctr(name):
        return counter_total(snapshot(), name)

    def compressed_view():
        # idempotent: the rebuilt engine after an elastic reset re-detects
        # the one-process world, so every step re-installs the >1 view
        # the codec resolution keys on
        eng = global_state().engine
        if eng.topology.size <= 1:
            eng.topology = dataclasses.replace(eng.topology, size=2)
        return eng

    rec_before = registry().counter(
        "hvd_tpu_elastic_recoveries_total").value(kind="internal")
    try:
        compressed_view()
        box = {"p": {"w": jnp.ones((6, 6))}, "i": 0}
        grad_fn = jax.jit(jax.grad(lambda p: jnp.sum(p["w"] ** 2)))

        def one_step():
            # the engine's compressed grouped path, bracketed as one step
            # (the DistributedEagerOptimizer short-circuits the engine on
            # one-rank worlds, so the chaos loop drives it directly)
            eng = compressed_view()
            g = grad_fn(box["p"])
            leaves, treedef = jax.tree_util.tree_flatten(g)
            eng.step_begin()
            try:
                hs = eng.grouped_allreduce(
                    leaves, name=f"cz.s{box['i']}",
                    op=hvd.ReduceOp.SUM, codec="int8")
                red = [h.result() for h in hs]
            finally:
                eng.step_end()
            box["i"] += 1
            g2 = jax.tree_util.tree_unflatten(treedef, red)
            box["p"] = jax.tree_util.tree_map(
                lambda p, gg: p - 0.05 * gg, box["p"], g2)

        # residual lineage exists BEFORE the fault
        for _ in range(3):
            one_step()
        jax.block_until_ready(box["p"]["w"])
        eng_before = global_state().engine
        assert len(eng_before._ef_residuals) > 0
        assert ctr("hvd_tpu_compression_codec_total") > 0
        faults.arm("compression.encode=1*raise(HorovodInternalError)")
        state = _CountingState(batch=0)
        target = 6

        @hvd.elastic.run
        def train(state):
            while state.batch < target:
                one_step()
                state.batch += 1
                state.commit()
            return state.batch

        assert train(state) == target
        jax.block_until_ready(box["p"]["w"])
        eng_after = global_state().engine
        assert state.restores == 1, "run-loop never restored"
        assert faults.hits("compression.encode") == 1
        assert registry().counter(
            "hvd_tpu_elastic_recoveries_total").value(kind="internal") \
            == rec_before + 1
        # fresh engine, fresh residual lineage — repopulated by the
        # post-restore compressed steps (invalidated, never poisoned)
        assert eng_after is not eng_before
        assert len(eng_after._ef_residuals) > 0
        assert bool(np.isfinite(np.asarray(box["p"]["w"])).all())
        # the counted world-version-bump invalidation edge on the NEW
        # lineage
        inval0 = ctr("hvd_tpu_compression_residual_invalidations_total")
        os.environ["HOROVOD_TPU_WORLD_VERSION"] = \
            str(eng_after.world_version + 2)
        one_step()
        assert ctr("hvd_tpu_compression_residual_invalidations_total") \
            > inval0
    finally:
        faults.disarm()
        os.environ.pop("HOROVOD_TPU_COMPRESSION", None)
        os.environ.pop("HOROVOD_TPU_WORLD_VERSION", None)
        hvd.shutdown()


# ---------------------------------------------------------------------------
# ISSUE 19 acceptance: SIGKILL the elastic DRIVER process mid-resize. The
# standby promotes from the replicated journal, the in-flight resize
# completes at the journaled world version, and every worker reaches its
# target step with zero process restarts and zero full-fleet restores.
# ---------------------------------------------------------------------------

_DRIVER_SCRIPT = """
import sys, time
from horovod_tpu import faults
from horovod_tpu.elastic.discovery import HostDiscoveryScript
from horovod_tpu.elastic.driver import ElasticDriver
from horovod_tpu.elastic.failover import DriverJournal
from horovod_tpu.elastic.rendezvous import ElasticRendezvousServer
from horovod_tpu.runner.replication import ReplicationConfig

p1, p2, hostsfile = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
reps = [f"127.0.0.1:{p1}", f"127.0.0.1:{p2}"]
server = ElasticRendezvousServer(("127.0.0.1", p1))
server.start()
server.enable_replication(
    reps[0], reps, role="primary",
    config=ReplicationConfig(lease_timeout=0.5, lease_interval=0.1))
print("REPL", flush=True)
sys.stdin.readline()              # test enables its standby, then GO
disc = HostDiscoveryScript(f"cat {hostsfile}")
driver = ElasticDriver(server, disc, min_np=2, max_np=4, timeout=30)
server.set_driver(driver)
driver.attach_journal(DriverJournal(server))
# the test spawns the worker processes itself, so they survive this
# process's SIGKILL like ssh-launched workers survive a driver-host crash
driver.start(2, lambda s: print(f"START {s.hostname}:{s.local_rank}",
                                flush=True))
print("V1", flush=True)
assert driver.wait_for_world(1, timeout=30)
# wedge the resize AT THIS DRIVER: every subsequent worker rendezvous GET
# here is dropped (long-polled), so the in-flight resize can only ever
# complete at the promoted standby — the kill window is deterministic
faults.arm("elastic.rendezvous.get=*drop()")
print("WEDGED", flush=True)
while True:
    time.sleep(1)
"""

_ELASTIC_WORKER_SCRIPT = """
import sys, threading, time
from horovod_tpu.elastic.worker import (SCOPE_WORKER_RESULTS,
                                        WorkerNotificationManager)
from horovod_tpu.runner.hosts import SlotInfo
from horovod_tpu.runner.http_client import (put_data_into_kvstore,
                                            read_data_from_kvstore,
                                            resolve_endpoints)

spec, host, lr, target = (sys.argv[1], sys.argv[2], int(sys.argv[3]),
                          int(sys.argv[4]))
eps = resolve_endpoints(spec)
interrupt = threading.Event()
seen = set()


class _Listener:
    def on_hosts_updated(self, ts, res):
        if ts not in seen:
            seen.add(ts)
            interrupt.set()


mgr = WorkerNotificationManager()
version, steps, first = 0, 0, True
while steps < target:
    raw = read_data_from_kvstore(eps, None, "rank_and_size",
                                 f"{host}:{lr}:{version}",
                                 timeout=120).decode()
    vs, _, slot_s = raw.partition("|")
    version = int(vs)
    slot = SlotInfo.from_response_string(slot_s)
    if slot.rank < 0:
        break                                   # scaled out: clean exit
    if first:
        # hostname here is only the ADVERTISED notification address (the
        # slot identity stays `host`); the logical test hostname does not
        # resolve, so advertise loopback
        mgr.init(rendezvous_addr=spec, rendezvous_port=0,
                 rank=slot.rank, hostname="127.0.0.1")
        mgr.register_listener(_Listener())
        first = False
    else:
        mgr.reregister(rank=slot.rank)
    interrupt.clear()
    while steps < target and not interrupt.is_set():
        time.sleep(0.05)                        # one training step
        steps += 1
put_data_into_kvstore(eps, None, SCOPE_WORKER_RESULTS, f"{host}:{lr}",
                      b"0", timeout=30)
print(f"DONE {steps}", flush=True)
"""


@pytest.mark.chaos
def test_driver_sigkill_mid_resize_failover(tmp_path, monkeypatch):
    """The ISSUE 19 acceptance run: a real SIGKILL of the elastic driver
    subprocess while a resize is in flight (pending journaled, workers
    long-polling a wedged driver). The standby must promote from the
    journal, resume the resize at the journaled world version, launch
    only the NEW host's worker, and finish with every worker at its
    target step — no worker process restarted, exactly one
    driver_failover recovery counted, and the promotion counters visible
    in the survivor's /metrics scrape."""
    import signal
    import subprocess
    import sys as _sys

    from horovod_tpu.elastic.discovery import HostDiscoveryScript
    from horovod_tpu.elastic.failover import DriverStandby
    from horovod_tpu.elastic.rendezvous import ElasticRendezvousServer
    from horovod_tpu.runner.http_server import find_free_port
    from horovod_tpu.runner.replication import ReplicationConfig

    monkeypatch.setenv("HOROVOD_TPU_DRIVER_LEASE_TIMEOUT", "0.6")
    monkeypatch.setenv("HOROVOD_TPU_DRIVER_LEASE_INTERVAL", "0.1")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    target = 120                                 # ~6s of stepping
    p1, p2 = find_free_port(), find_free_port()
    spec = f"127.0.0.1:{p1},127.0.0.1:{p2}"
    hostsfile = tmp_path / "hosts"
    hostsfile.write_text("hostA:2\n")
    driver_py = tmp_path / "driver.py"
    driver_py.write_text(_DRIVER_SCRIPT)
    worker_py = tmp_path / "worker.py"
    worker_py.write_text(_ELASTIC_WORKER_SCRIPT)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=repo_root + os.pathsep +
               os.environ.get("PYTHONPATH", ""),
               HOROVOD_TPU_DRIVER_LEASE_TIMEOUT="0.6",
               HOROVOD_TPU_DRIVER_LEASE_INTERVAL="0.1")
    env.pop("HOROVOD_TPU_FAULTS", None)

    def _spawn_worker(host, lr):
        return subprocess.Popen(
            [_sys.executable, str(worker_py), spec, host, str(lr),
             str(target)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            cwd=repo_root, env=env, text=True)

    reg = registry()
    promotions0 = reg.counter("hvd_tpu_driver_promotions_total").value()
    failovers0 = reg.counter("hvd_tpu_driver_failovers_total").value()
    recoveries0 = reg.counter("hvd_tpu_elastic_recoveries_total").value(
        kind="driver_failover")

    standby_server = ElasticRendezvousServer(("127.0.0.1", p2))
    standby_server.start()
    workers = {}
    standby = None
    proc = subprocess.Popen(
        [_sys.executable, str(driver_py), str(p1), str(p2),
         str(hostsfile)],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, cwd=repo_root, env=env, text=True)
    try:
        assert "REPL" in proc.stdout.readline()
        standby_server.enable_replication(
            f"127.0.0.1:{p2}", [f"127.0.0.1:{p1}", f"127.0.0.1:{p2}"],
            role="standby",
            config=ReplicationConfig(lease_timeout=0.5,
                                     lease_interval=0.1))

        def _on_new_slot(slot):
            # promoted-driver create_worker_fn: spawn ONLY new slots
            workers[(slot.hostname, slot.local_rank)] = _spawn_worker(
                slot.hostname, slot.local_rank)

        standby = DriverStandby(
            standby_server, HostDiscoveryScript(f"cat {hostsfile}"),
            min_np=2, max_np=4, timeout=30.0,
            create_worker_fn=_on_new_slot)
        standby.start()
        proc.stdin.write("GO\n")
        proc.stdin.flush()
        line = proc.stdout.readline()
        while "V1" not in line:
            assert line, "driver subprocess died during activation"
            line = proc.stdout.readline()
        for lr in (0, 1):
            workers[("hostA", lr)] = _spawn_worker("hostA", lr)
        assert "WEDGED" in proc.stdout.readline()

        # the resize: a new host appears; the wedged driver journals the
        # pending resume but can never complete it
        hostsfile.write_text("hostA:2\nhostB:1\n")
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            shadow = standby.shadow()
            if shadow.pending and "hostB" in shadow.hosts:
                break
            time.sleep(0.05)
        else:
            pytest.fail("pending resize never reached the standby journal")

        # SIGKILL the driver MID-RESIZE
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=10)

        # the standby monitor promotes: KV tier first (replica lease),
        # then the driver tier (journal lease stale)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and standby.driver is None:
            time.sleep(0.05)
        promoted = standby.driver
        assert promoted is not None, "standby never promoted"
        # the resize resumes AT THE JOURNALED VERSION: v1 was the wedged
        # world, the promoted driver completes the pending resume as v2
        assert wait_until_chaos(lambda: promoted.world_version == 2,
                                timeout=30)
        assert wait_until_chaos(lambda: not promoted.resume_needed(),
                                timeout=10)
        assert promoted.world_size() == 3
        # only the NEW host's worker was spawned by the promotion path
        assert set(workers) == {("hostA", 0), ("hostA", 1), ("hostB", 0)}
        # every worker reaches its target step in its ORIGINAL process
        assert wait_until_chaos(promoted.finished, timeout=60)
        assert promoted.error_message is None
        for key, wp in workers.items():
            assert wp.wait(timeout=30) == 0, f"worker {key} failed"
            out = wp.stdout.read()
            assert f"DONE {target}" in out, \
                f"worker {key} did not reach target in one life: {out!r}"

        # exactly one driver failover, zero fleet restores
        assert reg.counter("hvd_tpu_driver_promotions_total").value() \
            == promotions0 + 1
        assert reg.counter("hvd_tpu_driver_failovers_total").value() \
            == failovers0 + 1
        assert reg.counter("hvd_tpu_elastic_recoveries_total").value(
            kind="driver_failover") == recoveries0 + 1
        # the counters are visible in the SURVIVING replica's scrape
        import urllib.request
        with urllib.request.urlopen(
                f"http://127.0.0.1:{p2}/metrics/", timeout=10) as resp:
            scrape = resp.read().decode()
        assert "hvd_tpu_driver_promotions_total" in scrape
        assert "hvd_tpu_driver_failovers_total" in scrape
        assert 'kind="driver_failover"' in scrape
        # ...and the operator's health report reads the same story off the
        # survivor: journal head advanced, this replica is now primary,
        # exactly one promotion/failover on record.
        import importlib.util as _ilu
        spec = _ilu.spec_from_file_location(
            "health_report",
            os.path.join(repo_root, "tools", "health_report.py"))
        health = _ilu.module_from_spec(spec)
        spec.loader.exec_module(health)
        hr = health.assemble(f"http://127.0.0.1:{p2}")
        dr = hr["driver_replication"]
        assert dr["journal_head"] is not None and dr["journal_head"] > 0
        assert dr["repl_role"] == "primary"
        assert dr["promotions"] >= 1 and dr["failovers"] >= 1
        assert dr["failover_recoveries"] >= 1
        assert "driver replication:" in health.render(hr)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
        for wp in workers.values():
            if wp.poll() is None:
                wp.kill()
                wp.wait(timeout=10)
        if standby is not None:
            standby.stop()
        standby_server.stop()


def wait_until_chaos(cond, timeout=10.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False
