"""ZeRO-1 optimizer-state sharding (ISSUE 2 tentpole): sharded and
replicated training must produce identical parameter trajectories on the
CPU mesh — SPMD (``distributed(shard_optimizer=True)``) and eager
(``DistributedEagerOptimizer(sharded=True)``) — and the eager sharded path
must go through step-capture replay with a single dispatch per step.

The MLP's leaves (512 + 32 + 128 + 4 floats) deliberately do NOT divide
the 8-rank world, so every test also exercises the divisibility padding
(ops/collectives.shard_spec).
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import horovod_tpu as hvd  # installs the jax compat shims first
from jax import shard_map
from horovod_tpu import optimizer as hopt
from horovod_tpu.optimizer import (DistributedEagerOptimizer,
                                   ShardedEagerState, zero1_state_specs)
from horovod_tpu.models.mlp import init_mlp, mlp_loss
from horovod_tpu.ops.compression import Compression


def _params():
    return init_mlp(jax.random.PRNGKey(0), sizes=(16, 32, 4))


def _batch(n=64, din=16, nclass=4, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(n, din).astype(np.float32),
            rng.randint(0, nclass, size=(n,)).astype(np.int32))


# ---------------------------------------------------------------------------
# SPMD path
# ---------------------------------------------------------------------------


def _spmd_train(dist, params, x, y, mesh, state_specs, steps=4,
                init_inside=False):
    def local_step(p, s, xb, yb):
        g = jax.grad(mlp_loss)(p, (xb, yb))
        u, s = dist.update(g, s, p)
        return optax.apply_updates(p, u), s

    step = jax.jit(shard_map(
        local_step, mesh=mesh,
        in_specs=(P(), state_specs, P("world"), P("world")),
        out_specs=(P(), state_specs), check_vma=False))
    sh = NamedSharding(mesh, P("world"))
    xb, yb = jax.device_put(x, sh), jax.device_put(y, sh)
    p = jax.device_put(params, NamedSharding(mesh, P()))
    if init_inside:
        s = jax.jit(shard_map(dist.init, mesh=mesh, in_specs=(P(),),
                              out_specs=state_specs, check_vma=False))(p)
    else:
        s = dist.init(p)
    for _ in range(steps):
        p, s = step(p, s, xb, yb)
    return p


@pytest.mark.parametrize("make_inner", [
    lambda: optax.adam(1e-2),
    lambda: optax.sgd(0.05, momentum=0.9),
], ids=["adam", "sgd_momentum"])
def test_spmd_sharded_matches_dense(make_inner):
    """The numeric acceptance bar: sharded (rs -> shard update -> ag) and
    replicated (allreduce -> full update) trajectories match on the 8-dev
    CPU mesh, including the non-divisible bucket padding."""
    mesh = Mesh(np.array(jax.devices()), ("world",))
    params = _params()
    x, y = _batch()

    dense = hopt.distributed(make_inner(), axis_name="world", op=hvd.Average)
    dp = _spmd_train(dense, params, x, y, mesh, P())

    zer = hopt.distributed(make_inner(), axis_name="world", op=hvd.Average,
                           axis_size=8, shard_optimizer=True)
    zspecs = zero1_state_specs(jax.eval_shape(zer.init, params), "world")
    zp = _spmd_train(zer, params, x, y, mesh, zspecs, init_inside=True)

    for a, b in zip(jax.tree_util.tree_leaves(dp),
                    jax.tree_util.tree_leaves(zp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_spmd_sharded_init_outside_axis_matches():
    """init() outside the mesh axis materializes zero shard placeholders —
    exact for the zeros-initialized elementwise inner family, so the
    trajectory still matches dense."""
    mesh = Mesh(np.array(jax.devices()), ("world",))
    params = _params()
    x, y = _batch(seed=2)
    dense = hopt.distributed(optax.adam(1e-2), axis_name="world",
                             op=hvd.Average)
    dp = _spmd_train(dense, params, x, y, mesh, P())
    zer = hopt.distributed(optax.adam(1e-2), axis_name="world",
                           op=hvd.Average, axis_size=8, shard_optimizer=True)
    zspecs = zero1_state_specs(jax.eval_shape(zer.init, params), "world")
    # outside-axis init: shard-shaped zeros, replicated in -> the step's
    # in_specs then see identical (zero) shards on each rank, which is the
    # true per-shard init for adam/sgd
    st = zer.init(params)
    st = jax.tree_util.tree_map(lambda l: np.asarray(l), st)

    def local_step(p, s, xb, yb):
        g = jax.grad(mlp_loss)(p, (xb, yb))
        u, s = zer.update(g, s, p)
        return optax.apply_updates(p, u), s

    # state travels replicated here (every rank holds the same zeros at
    # t=0 and evolves its own copy of its shard thereafter — but with P()
    # out-specs the per-rank shards would be merged; use the stacked specs
    # by lifting the zero shards to the stacked global layout instead)
    def lift(l):
        # scalars stay replicated (zero1_state_specs rule); shard arrays
        # stack 8 identical zero shards into the P("world") global layout
        if getattr(l, "ndim", 0) == 0:
            return jnp.asarray(l)
        return jnp.tile(jnp.asarray(l), (8,) + (1,) * (l.ndim - 1))

    st = jax.tree_util.tree_map(lift, st)
    step = jax.jit(shard_map(
        local_step, mesh=mesh,
        in_specs=(P(), zspecs, P("world"), P("world")),
        out_specs=(P(), zspecs), check_vma=False))
    sh = NamedSharding(mesh, P("world"))
    xb, yb = jax.device_put(x, sh), jax.device_put(y, sh)
    p = jax.device_put(params, NamedSharding(mesh, P()))
    for _ in range(4):
        p, st = step(p, st, xb, yb)
    for a, b in zip(jax.tree_util.tree_leaves(dp),
                    jax.tree_util.tree_leaves(p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_spmd_sharded_validation():
    with pytest.raises(ValueError, match="axis_size"):
        hopt.distributed(optax.adam(1e-2), shard_optimizer=True)
    with pytest.raises(ValueError, match="Average|Sum"):
        hopt.distributed(optax.adam(1e-2), shard_optimizer=True,
                         axis_size=8, op=hvd.Adasum)
    with pytest.raises(ValueError, match="compression"):
        hopt.distributed(optax.adam(1e-2), shard_optimizer=True,
                         axis_size=8, compression=Compression.bf16)
    with pytest.raises(ValueError, match="backward_passes_per_step"):
        hopt.distributed(optax.adam(1e-2), shard_optimizer=True,
                         axis_size=8, backward_passes_per_step=2)


# ---------------------------------------------------------------------------
# Eager path
# ---------------------------------------------------------------------------


@pytest.fixture()
def engine():
    hvd.init()
    eng = hvd._engine()
    prev_warm, prev_on = (eng.config.step_replay_warmup,
                          eng.config.step_replay)
    eng.config.step_replay_warmup = 2
    eng.config.step_replay = True
    eng.replay.invalidate_all("test isolation")
    yield eng
    eng.replay.invalidate_all("test isolation")
    eng.config.step_replay_warmup = prev_warm
    eng.config.step_replay = prev_on


def _eager_train(opt, params, x, y, steps):
    grad_fn = jax.jit(jax.grad(mlp_loss))
    p, s = params, opt.init(params)
    for _ in range(steps):
        g = grad_fn(p, (jnp.asarray(x), jnp.asarray(y)))
        p, s = opt.update_and_apply(g, s, p)
    jax.block_until_ready(p)
    return p, s


def test_eager_sharded_matches_dense(engine):
    params = _params()
    x, y = _batch(seed=5)
    dp, _ = _eager_train(DistributedEagerOptimizer(optax.adam(1e-2)),
                         params, x, y, 5)
    sp, ss = _eager_train(
        DistributedEagerOptimizer(optax.adam(1e-2), sharded=True),
        params, x, y, 5)
    assert isinstance(ss, ShardedEagerState)
    for a, b in zip(jax.tree_util.tree_leaves(dp),
                    jax.tree_util.tree_leaves(sp)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_eager_sharded_state_layout(engine):
    """init materializes shard-sized state: one flat master-copy shard of
    ceil(total/world) per fusion bucket, inner state over the shards."""
    params = _params()
    opt = DistributedEagerOptimizer(optax.adam(1e-2), sharded=True)
    st = opt.init(params)
    size = engine.backend.size()
    total = sum(int(np.prod(l.shape))
                for l in jax.tree_util.tree_leaves(params))
    assert len(st.shards) == 1  # everything fits one 64 MB bucket
    assert st.shards[0].shape == (-(-total // size),)
    # adam: mu/nu mirror the shard vectors, not the tensor shapes
    mu_leaves = jax.tree_util.tree_leaves(st.inner_state)
    assert any(l.shape == st.shards[0].shape for l in mu_leaves)


def test_eager_sharded_replay_single_dispatch(engine):
    """Acceptance bar: the sharded eager step goes through replay with
    engine.dispatch_count of 1 per steady-state step."""
    params = _params()
    x, y = _batch(seed=6)
    opt = DistributedEagerOptimizer(optax.sgd(0.05, momentum=0.9),
                                    sharded=True)
    grad_fn = jax.jit(jax.grad(mlp_loss))
    p, s = params, opt.init(params)
    for _ in range(4):  # warmup=2: record, record, arm+replay...
        g = grad_fn(p, (jnp.asarray(x), jnp.asarray(y)))
        p, s = opt.update_and_apply(g, s, p)
    jax.block_until_ready(p)
    assert engine.replay.replayed_steps >= 1
    g = grad_fn(p, (jnp.asarray(x), jnp.asarray(y)))
    d0 = engine.dispatch_count
    p, s = opt.update_and_apply(g, s, p)
    assert engine.dispatch_count - d0 == 1, \
        "a steady-state sharded step must be ONE engine dispatch"
    jax.block_until_ready(p)
    # and the replayed step still matches the recorded path numerically
    dp, _ = _eager_train(
        DistributedEagerOptimizer(optax.sgd(0.05, momentum=0.9)),
        params, x, y, 5)
    for a, b in zip(jax.tree_util.tree_leaves(dp),
                    jax.tree_util.tree_leaves(p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_eager_sharded_with_accumulation(engine):
    """backward_passes_per_step composes with sharding (accumulation is
    host-side, before the reduce-scatter)."""
    params = _params()
    x, y = _batch(seed=7)
    grad_fn = jax.jit(jax.grad(mlp_loss))

    dense = DistributedEagerOptimizer(optax.sgd(0.1),
                                      backward_passes_per_step=2)
    shard = DistributedEagerOptimizer(optax.sgd(0.1),
                                      backward_passes_per_step=2,
                                      sharded=True)
    dp, ds = params, dense.init(params)
    sp, ss = params, shard.init(params)
    for _ in range(4):
        g = grad_fn(dp, (jnp.asarray(x), jnp.asarray(y)))
        dp, ds = dense.update_and_apply(g, ds, dp)
        g = grad_fn(sp, (jnp.asarray(x), jnp.asarray(y)))
        sp, ss = shard.update_and_apply(g, ss, sp)
    for a, b in zip(jax.tree_util.tree_leaves(dp),
                    jax.tree_util.tree_leaves(sp)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_eager_sharded_validation(engine):
    with pytest.raises(ValueError, match="compression"):
        DistributedEagerOptimizer(optax.sgd(0.1), sharded=True,
                                  compression=Compression.bf16)
    with pytest.raises(ValueError, match="sparse_rows"):
        DistributedEagerOptimizer(optax.sgd(0.1), sharded=True,
                                  sparse_rows={"embed": 4})
    with pytest.raises(ValueError, match="Average|Sum"):
        DistributedEagerOptimizer(optax.sgd(0.1), sharded=True,
                                  op=hvd.Adasum)
    # a non-sharded state fed to a sharded optimizer fails loudly
    params = _params()
    opt = DistributedEagerOptimizer(optax.sgd(0.1), sharded=True)
    dense_state = optax.sgd(0.1).init(params)
    g = jax.tree_util.tree_map(jnp.ones_like, params)
    with pytest.raises(ValueError, match="non-sharded state"):
        opt.update_and_apply(g, dense_state, params)


def test_eager_sharded_survives_threshold_move(engine):
    """The bucket layout is FROZEN at state init: a live fusion-threshold
    move (autotune retunes it every sample) must neither crash nor
    re-bucket a sharded run — the cached layout keeps serving the live
    state."""
    params = _params()
    opt = DistributedEagerOptimizer(optax.sgd(0.1), sharded=True)
    st = opt.init(params)
    g = jax.tree_util.tree_map(jnp.ones_like, params)
    prev = engine.config.fusion_threshold_bytes
    engine.config.fusion_threshold_bytes = 256  # would force tiny buckets
    try:
        p2, st2 = opt.update_and_apply(g, st, params)
        jax.block_until_ready(jax.tree_util.tree_leaves(p2)[0])
        assert st2.shards[0].shape == st.shards[0].shape
    finally:
        engine.config.fusion_threshold_bytes = prev


def test_eager_sharded_lost_layout_raises(engine):
    """If the frozen layout is genuinely gone (cache evicted across a
    threshold move — or state from another world size), the shape
    validation fails loudly instead of corrupting the shards."""
    params = _params()
    opt = DistributedEagerOptimizer(optax.sgd(0.1), sharded=True)
    st = opt.init(params)
    g = jax.tree_util.tree_map(jnp.ones_like, params)
    prev = engine.config.fusion_threshold_bytes
    opt._layout_cache.clear()                   # simulate LRU eviction
    engine.config.fusion_threshold_bytes = 256  # recompute re-buckets
    try:
        with pytest.raises(ValueError, match="layout mismatch"):
            opt.update_and_apply(g, st, params)
    finally:
        engine.config.fusion_threshold_bytes = prev


def test_broadcast_optimizer_state_refuses_sharded(engine):
    """broadcast_optimizer_state on a ZeRO-1 state would overwrite every
    rank's distinct parameter-master shard with rank 0's — it must refuse
    loudly and point at the broadcast-params-then-reinit recipe."""
    params = _params()
    opt = DistributedEagerOptimizer(optax.adam(1e-2), sharded=True)
    st = opt.init(params)
    with pytest.raises(ValueError, match="rank-local shards"):
        hvd.broadcast_optimizer_state(st, root_rank=0)


def test_config_knob_defaults_sharded(engine, monkeypatch):
    """sharded=None defers to the HOROVOD_TPU_SHARD_OPTIMIZER-backed
    config (the autotune categorical's target)."""
    params = _params()
    monkeypatch.setattr(engine.config, "shard_optimizer", True)
    opt = DistributedEagerOptimizer(optax.sgd(0.1))
    st = opt.init(params)
    assert isinstance(st, ShardedEagerState)
    monkeypatch.setattr(engine.config, "shard_optimizer", False)
    opt2 = DistributedEagerOptimizer(optax.sgd(0.1))
    assert not isinstance(opt2.init(params), ShardedEagerState)
