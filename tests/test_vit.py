"""ViT model family: forward/backward through the shared attention kernels."""

import numpy as np
import jax
import jax.numpy as jnp


def test_vit_forward_backward():
    from horovod_tpu.models.vit import ViT_Tiny
    m = ViT_Tiny(num_classes=10, dtype=jnp.float32)
    x = jnp.asarray(np.random.RandomState(0).rand(2, 32, 32, 3), jnp.float32)
    params = m.init(jax.random.PRNGKey(0), x)
    logits = m.apply(params, x)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32

    def loss(p):
        return jnp.mean(m.apply(p, x) ** 2)

    g = jax.grad(loss)(params)
    leaves = jax.tree_util.tree_leaves(g)
    assert leaves and all(np.isfinite(np.asarray(l)).all() for l in leaves)
    # gradients actually flow to the patchifier and the head
    flat = jax.tree_util.tree_flatten_with_path(g)[0]
    names = ["/".join(str(k.key) for k in path if hasattr(k, "key"))
             for path, _ in flat]
    assert any("patchify" in n for n in names)
    assert any("head" in n for n in names)


def test_vit_token_count():
    from horovod_tpu.models.vit import ViT_Tiny
    m = ViT_Tiny(num_classes=4, dtype=jnp.float32)
    x = jnp.ones((1, 32, 32, 3))
    v = m.init(jax.random.PRNGKey(0), x)
    # 32/8 = 4 -> 16 patches + 1 cls token
    assert v["params"]["pos_embed"].shape == (1, 17, 64)


def test_flash_gate_unaligned_seq_falls_back(monkeypatch):
    """ADVICE r3 (medium): ViT sequence lengths (197, 17) are not multiples
    of the Pallas flash kernel's 128 block, so flash_attention_local must
    take the materialized fallback instead of crashing. Simulated here by
    forcing flash_available()=True on CPU: without the shape gate this
    imports and calls the TPU kernel (and dies); with it, the fallback runs
    and matches local_attention exactly."""
    from horovod_tpu.parallel import flash_attention as fa
    from horovod_tpu.parallel.ring_attention import local_attention
    monkeypatch.setattr(fa, "flash_available", lambda: True)
    rng = np.random.RandomState(0)
    for t in (197, 17):
        q, k, v = (jnp.asarray(rng.rand(2, t, 4, 32), jnp.float32)
                   for _ in range(3))
        out = fa.flash_attention_local(q, k, v, causal=False)
        ref = local_attention(q, k, v, causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
