"""Differentiation of the collective builders/primitives — the TPU-native
analog of the reference's registered-gradient tests (tensorflow/mpi_ops.py:
107-119 allreduce-grad=allreduce, :141-164 allgather-grad=slice of
allreduce, :184-199 broadcast-grad routes to root; exercised by the
grad-check grids of test/test_tensorflow.py).

Under JAX the gradients come from AD through psum/all_gather directly; these
tests pin the same contracts numerically on the 8-device world.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.common.reduce_ops import ReduceOp
from horovod_tpu.ops import collectives as C
from horovod_tpu.parallel.mesh import WORLD_AXIS

N = 8


def stacked(mesh, per_rank):
    return jax.device_put(jnp.asarray(per_rank),
                          NamedSharding(mesh, P(WORLD_AXIS)))


def test_allreduce_sum_gradient(mesh8):
    """L = Σ_i w_i · allreduce(x)_i ⇒ dL/dx[r] = w for every rank (the
    allreduce-grad-is-allreduce contract)."""
    rng = np.random.RandomState(0)
    x = rng.randn(N, 5).astype(np.float32)
    w = jnp.asarray(rng.randn(5).astype(np.float32))
    fn = C.build_allreduce(mesh8, WORLD_AXIS, ReduceOp.SUM)
    g = jax.grad(lambda s: jnp.sum(fn(s) * w))(stacked(mesh8, x))
    g = np.asarray(g)
    for r in range(N):
        np.testing.assert_allclose(g[r], np.asarray(w), rtol=1e-5)


def test_allreduce_average_gradient(mesh8):
    x = np.random.RandomState(1).randn(N, 4).astype(np.float32)
    w = jnp.asarray(np.random.RandomState(2).randn(4).astype(np.float32))
    fn = C.build_allreduce(mesh8, WORLD_AXIS, ReduceOp.AVERAGE)
    g = np.asarray(jax.grad(lambda s: jnp.sum(fn(s) * w))(stacked(mesh8, x)))
    for r in range(N):
        np.testing.assert_allclose(g[r], np.asarray(w) / N, rtol=1e-5)


def test_broadcast_gradient_routes_to_root(mesh8):
    root = 3
    x = np.random.RandomState(3).randn(N, 6).astype(np.float32)
    w = jnp.asarray(np.random.RandomState(4).randn(6).astype(np.float32))
    fn = C.build_broadcast(mesh8, WORLD_AXIS, root)
    g = np.asarray(jax.grad(lambda s: jnp.sum(fn(s) * w))(stacked(mesh8, x)))
    for r in range(N):
        expected = np.asarray(w) if r == root else np.zeros(6, np.float32)
        np.testing.assert_allclose(g[r], expected, rtol=1e-5)


def test_allgather_gradient_is_slice(mesh8):
    """L = Σ w_full · allgather(x) ⇒ dL/dx[r] = the slice of w that rank r's
    rows occupy (mpi_ops.py:141-164 contract)."""
    d0 = 2
    x = np.random.RandomState(5).randn(N, d0, 3).astype(np.float32)
    w = jnp.asarray(np.random.RandomState(6).randn(N * d0, 3)
                    .astype(np.float32))
    fn = C.build_allgather(mesh8, WORLD_AXIS)
    g = np.asarray(jax.grad(lambda s: jnp.sum(fn(s) * w))(stacked(mesh8, x)))
    for r in range(N):
        np.testing.assert_allclose(g[r], np.asarray(w)[r * d0:(r + 1) * d0],
                                   rtol=1e-5)


def test_reducescatter_gradient(mesh8):
    """reducescatter-grad = allgather of the upstream shard grads."""
    x = np.random.RandomState(7).randn(N, N, 2).astype(np.float32)
    w = jnp.asarray(np.random.RandomState(8).randn(N, 1, 2)
                    .astype(np.float32))
    fn = C.build_reducescatter(mesh8, WORLD_AXIS, ReduceOp.SUM)
    # output: stacked (N, N/N=1, 2) per rank shard
    g = np.asarray(jax.grad(lambda s: jnp.sum(fn(s) * w))(stacked(mesh8, x)))
    expected = np.asarray(w).reshape(N, 2)  # shard j's grad lands on row j
    for r in range(N):
        np.testing.assert_allclose(g[r], expected, rtol=1e-5)


def test_spmd_primitive_allreduce_grad_inside_shard_map(mesh8):
    """allreduce_p is differentiable inside a user shard_map (the functional
    DistributedGradientTape contract)."""
    from jax import shard_map

    def loss_fn(x):  # x block (1, 4)
        y = C.allreduce_p(x[0], WORLD_AXIS, ReduceOp.AVERAGE)
        return jax.lax.pmean(jnp.sum(y ** 2), WORLD_AXIS)

    f = jax.jit(shard_map(loss_fn, mesh=mesh8, in_specs=P(WORLD_AXIS),
                          out_specs=P()))
    x = np.random.RandomState(9).randn(N, 4).astype(np.float32)
    g = np.asarray(jax.grad(lambda s: f(s))(stacked(mesh8, x)))
    mean = x.mean(axis=0)
    # d/dx[r] of sum(mean^2) = 2*mean/N
    for r in range(N):
        np.testing.assert_allclose(g[r], 2 * mean / N, rtol=1e-4)
