"""The lock-discipline checker itself (ISSUE 7 tentpole): every violation
class must be detected with file:line on the known fixtures, the clean
fixture must produce zero findings, and the live ``horovod_tpu/`` tree
must be clean with every suppression carrying a reason.
"""

import os

import pytest

from horovod_tpu.analysis import lockcheck

pytestmark = pytest.mark.lint

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "lockcheck")
PKG_ROOT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "horovod_tpu")


def _check_fixture(name):
    path = os.path.join(FIXTURES, name)
    rep = lockcheck.check_paths([path], root=FIXTURES)
    return rep, open(path).read().splitlines()


def _line_of(lines, needle, nth=0):
    hits = [i + 1 for i, l in enumerate(lines) if needle in l]
    assert hits, f"fixture drifted: {needle!r} not found"
    return hits[nth]


class TestViolationClasses:
    def test_off_lock_write_and_read(self):
        rep, lines = _check_fixture("bad_offlock.py")
        checks = {(f.check, f.line) for f in rep.findings}
        assert ("off-lock-access",
                _line_of(lines, "VIOLATION: off-lock write")) in checks
        assert ("off-lock-access",
                _line_of(lines, "VIOLATION: off-lock read")) in checks
        assert len(rep.findings) == 2  # the locked methods are clean

    def test_lock_order_inversion_and_reacquire(self):
        rep, lines = _check_fixture("bad_order.py")
        order = [f for f in rep.findings if f.check == "lock-order"]
        msgs = "\n".join(f.message for f in order)
        assert "TwoLocks._b_lock -> TwoLocks._a_lock" in msgs
        assert "re-acquires non-reentrant lock self._a_lock" in msgs
        lineno = _line_of(lines, "VIOLATION: non-reentrant re-acquire")
        assert any(f.line == lineno for f in order)

    def test_blocking_under_lock(self):
        rep, lines = _check_fixture("bad_blocking.py")
        blk = [f for f in rep.findings if f.check == "blocking-under-lock"]
        assert {f.attr for f in blk} == {"sleep", "join"}
        assert _line_of(lines, "sleep under lock") in {f.line for f in blk}
        assert _line_of(lines, "thread join under lock") in \
            {f.line for f in blk}
        # sleep() outside any lock is not flagged
        assert all("good_sleep" not in f.message for f in rep.findings)

    def test_unannotated_thread_target(self):
        rep, lines = _check_fixture("bad_thread.py")
        f, = [f for f in rep.findings
              if f.check == "unannotated-thread-shared"]
        assert f.attr == "_state"
        assert f.line == \
            _line_of(lines, "VIOLATION: unannotated shared attribute")
        assert "_loop" in f.message and "read_state" in f.message

    def test_requires_unheld(self):
        rep, lines = _check_fixture("bad_requires.py")
        f, = [f for f in rep.findings if f.check == "requires-unheld"]
        assert f.line == _line_of(lines, "called without it")
        assert "_evict_one" in f.message
        # the locked call site is clean
        assert all(x.line != _line_of(lines, "self._evict_one()", 0)
                   or x is f for x in rep.findings)

    def test_stale_and_reasonless_suppressions(self):
        rep, lines = _check_fixture("bad_suppression.py")
        checks = {f.check: f.line for f in rep.findings}
        assert checks["stale-suppression"] == \
            _line_of(lines, "lockcheck: ignore[old excuse")
        assert checks["bad-suppression"] == \
            _line_of(lines, "# lockcheck: ignore", 1)
        # the reasonless one never lands in the suppression list
        assert rep.suppressions == []

    def test_clean_fixture_zero_findings(self):
        rep, _ = _check_fixture("clean.py")
        assert rep.findings == []
        assert rep.suppressions == []
        assert rep.guarded_attrs >= 4  # dict + trailing-comment annotation


class TestConventions:
    def test_trailing_guarded_by_comment_is_an_annotation(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._x = 0  # guarded_by: _lock\n"
            "    def bad(self):\n"
            "        return self._x\n")
        findings, _sups, _n, n_guarded = lockcheck.check_source(src, "m.py")
        assert n_guarded == 1
        assert [f.check for f in findings] == ["off-lock-access"]

    def test_internally_synced_is_exempt_but_annotated(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    _GUARDED_BY = {'_q': '<internal>'}\n"
            "    def __init__(self):\n"
            "        self._q = []\n"
            "        self._t = threading.Thread(target=self._loop)\n"
            "    def _loop(self):\n"
            "        self._q.append(1)\n"
            "    def drain(self):\n"
            "        self._q.clear()\n")
        findings, _sups, _n, _g = lockcheck.check_source(src, "m.py")
        assert findings == []

    def test_acquire_release_linear_tracking(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    _GUARDED_BY = {'_x': '_lock'}\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._x = 0\n"
            "    def ok(self):\n"
            "        self._lock.acquire()\n"
            "        try:\n"
            "            self._x += 1\n"
            "        finally:\n"
            "            self._lock.release()\n"
            "    def bad(self):\n"
            "        self._lock.acquire()\n"
            "        self._lock.release()\n"
            "        self._x += 1\n")
        findings, *_ = lockcheck.check_source(src, "m.py")
        assert [(f.check, "bad" in f.message) for f in findings] == \
            [("off-lock-access", True)]

    def test_release_in_finally_propagates(self):
        # the acquire();try:...finally:release() idiom: after the try the
        # lock is RELEASED — accesses below are off-lock findings, and
        # blocking calls below are NOT blocking-under-lock
        src = (
            "import threading, time\n"
            "class C:\n"
            "    _GUARDED_BY = {'_x': '_lock'}\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._x = 0\n"
            "    def m(self):\n"
            "        self._lock.acquire()\n"
            "        try:\n"
            "            self._x += 1\n"
            "        finally:\n"
            "            self._lock.release()\n"
            "        self._x += 1\n"
            "        time.sleep(1)\n")
        findings, *_ = lockcheck.check_source(src, "m.py")
        assert [(f.check, f.line) for f in findings] == \
            [("off-lock-access", 13)]

    def test_multi_item_with_records_edges_and_reacquire(self):
        # `with self._a_lock, self._b_lock:` is the nested form: the
        # inversion against the other method and a same-statement
        # re-acquire must both be caught
        src = (
            "import threading\n"
            "class T:\n"
            "    def __init__(self):\n"
            "        self._a_lock = threading.Lock()\n"
            "        self._b_lock = threading.Lock()\n"
            "    def fwd(self):\n"
            "        with self._a_lock, self._b_lock:\n"
            "            pass\n"
            "    def bwd(self):\n"
            "        with self._b_lock, self._a_lock:\n"
            "            pass\n"
            "    def re(self):\n"
            "        with self._a_lock, self._a_lock:\n"
            "            pass\n")
        findings, *_ = lockcheck.check_source(src, "t.py")
        checks = sorted((f.check, f.line) for f in findings)
        assert ("lock-order", 13) in checks          # re-acquire
        assert any(c == "lock-order" and l in (7, 10) for c, l in checks)

    def test_same_named_classes_do_not_merge_order_graphs(self, tmp_path):
        # two unrelated classes sharing a name in different files must not
        # produce a phantom cross-file inversion — no thread can hold both
        # classes' locks through `self`
        for name, order in (("x.py", ("_lock", "_sub_lock")),
                            ("y.py", ("_sub_lock", "_lock"))):
            (tmp_path / name).write_text(
                "import threading\n"
                "class S:\n"
                "    def __init__(self):\n"
                "        self._lock = threading.Lock()\n"
                "        self._sub_lock = threading.Lock()\n"
                "    def m(self):\n"
                f"        with self.{order[0]}:\n"
                f"            with self.{order[1]}:\n"
                "                pass\n")
        rep = lockcheck.check_paths([str(tmp_path)], root=str(tmp_path))
        assert rep.findings == [], rep.findings

    def test_annotated_guarded_by_assignment(self):
        # `_GUARDED_BY: Dict[str, str] = {...}` (a routine typing cleanup)
        # must keep the checks on
        src = (
            "import threading\n"
            "from typing import Dict\n"
            "class C:\n"
            "    _GUARDED_BY: Dict[str, str] = {'_x': '_lock'}\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._x = 0\n"
            "    def bad(self):\n"
            "        return self._x\n")
        findings, _s, _n, n_guarded = lockcheck.check_source(src, "m.py")
        assert n_guarded == 1
        assert [f.check for f in findings] == ["off-lock-access"]

    def test_annotated_instance_assignments_keep_their_annotations(self):
        # `self._cv: threading.Condition = threading.Condition()` must
        # classify as a lock, and a trailing guarded_by on an annotated
        # assignment must register — typing cleanups never disarm checks
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._cv: threading.Condition = threading.Condition()\n"
            "        self._x: int = 0  # guarded_by: _cv\n"
            "    def good(self):\n"
            "        with self._cv:\n"
            "            self._x += 1\n"
            "    def bad(self):\n"
            "        return self._x\n")
        findings, _s, _n, n_guarded = lockcheck.check_source(src, "m.py")
        assert n_guarded == 1
        assert [(f.check, f.line) for f in findings] == \
            [("off-lock-access", 10)]

    def test_inversion_documented_at_both_sites_is_not_stale(self):
        src = (
            "import threading\n"
            "class T:\n"
            "    def __init__(self):\n"
            "        self._a_lock = threading.Lock()\n"
            "        self._b_lock = threading.Lock()\n"
            "    def fwd(self):\n"
            "        with self._a_lock:\n"
            "            # lockcheck: ignore[documented inversion end A]\n"
            "            with self._b_lock:\n"
            "                pass\n"
            "    def bwd(self):\n"
            "        with self._b_lock:\n"
            "            # lockcheck: ignore[documented inversion end B]\n"
            "            with self._a_lock:\n"
            "                pass\n")
        findings, sups, *_ = lockcheck.check_source(src, "t.py")
        assert findings == []           # in particular: no stale-suppression
        assert len(sups) == 1 and sups[0].check == "lock-order"

    def test_deep_inheritance_chain_inherits_guards(self):
        # reverse-declared 4-hop chain: the base merge must iterate to a
        # fixpoint, not a fixed pass count
        src = (
            "import threading\n"
            "class E(D):\n"
            "    def bad(self):\n"
            "        return self._x\n"
            "class D(C):\n"
            "    pass\n"
            "class C(B):\n"
            "    pass\n"
            "class B(A):\n"
            "    pass\n"
            "class A:\n"
            "    _GUARDED_BY = {'_x': '_lock'}\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._x = 0\n")
        findings, *_ = lockcheck.check_source(src, "m.py")
        assert [(f.check, f.line) for f in findings] == \
            [("off-lock-access", 4)]

    def test_match_case_bodies_are_checked(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    _GUARDED_BY = {'_x': '_lock'}\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._x = 0\n"
            "        self._mode = 'a'\n"
            "    def m(self):\n"
            "        match self._mode:\n"
            "            case 'a':\n"
            "                self._x += 1\n"
            "            case _:\n"
            "                pass\n")
        findings, *_ = lockcheck.check_source(src, "m.py")
        assert [(f.check, f.line) for f in findings] == \
            [("off-lock-access", 11)]

    def test_init_is_exempt(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    _GUARDED_BY = {'_x': '_lock'}\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._x = 0\n")
        findings, *_ = lockcheck.check_source(src, "m.py")
        assert findings == []

    def test_nested_function_runs_lockless(self):
        # a closure defined under the lock does NOT inherit the held set —
        # it may run later on any thread
        src = (
            "import threading\n"
            "class C:\n"
            "    _GUARDED_BY = {'_x': '_lock'}\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._x = 0\n"
            "    def make(self):\n"
            "        with self._lock:\n"
            "            def cb():\n"
            "                return self._x\n"
            "            return cb\n")
        findings, *_ = lockcheck.check_source(src, "m.py")
        assert [f.check for f in findings] == ["off-lock-access"]

    def test_trailing_suppression_does_not_bleed_to_next_line(self):
        # a TRAILING ignore excuses its own line only; the off-lock write
        # directly below must still be reported
        src = (
            "import threading\n"
            "class C:\n"
            "    _GUARDED_BY = {'_x': '_lock', '_y': '_lock'}\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._x = 0\n"
            "        self._y = 0\n"
            "    def m(self):\n"
            "        a = self._x  # lockcheck: ignore[benign racy read]\n"
            "        self._y = a\n")
        findings, sups, *_ = lockcheck.check_source(src, "m.py")
        assert [(f.check, f.line) for f in findings] == \
            [("off-lock-access", 10)]
        assert len(sups) == 1 and sups[0].attr == "_x"

    def test_lock_order_suppressible_at_either_edge(self):
        # an inversion spans two acquisition sites; the ignore comment at
        # EITHER site suppresses it and is not reported stale
        src = (
            "import threading\n"
            "class T:\n"
            "    def __init__(self):\n"
            "        self._a_lock = threading.Lock()\n"
            "        self._b_lock = threading.Lock()\n"
            "    def fwd(self):\n"
            "        with self._a_lock:\n"
            "            with self._b_lock:\n"
            "                pass\n"
            "    def bwd(self):\n"
            "        with self._b_lock:\n"
            "            # lockcheck: ignore[documented deliberate inversion]\n"
            "            with self._a_lock:\n"
            "                pass\n")
        findings, sups, *_ = lockcheck.check_source(src, "t.py")
        assert findings == []
        assert [(s.check, s.reason) for s in sups] == \
            [("lock-order", "documented deliberate inversion")]

    def test_unparseable_file_is_a_finding_not_a_crash(self):
        findings, *_ = lockcheck.check_source(
            "def broken(:\n  '''unterminated\n", "b.py")
        assert [f.check for f in findings] == ["parse-error"]

    def test_suppression_with_reason_is_counted(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    _GUARDED_BY = {'_x': '_lock'}\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._x = 0\n"
            "    def fast(self):\n"
            "        return self._x  # lockcheck: ignore[benign racy read]\n")
        findings, sups, *_ = lockcheck.check_source(src, "m.py")
        assert findings == []
        assert len(sups) == 1 and sups[0].reason == "benign racy read"


class TestLiveTree:
    def test_horovod_tpu_is_lock_discipline_clean(self):
        rep = lockcheck.check_package(PKG_ROOT)
        assert rep.findings == [], "\n".join(str(f) for f in rep.findings)

    def test_every_live_suppression_carries_a_reason(self):
        # the acceptance criterion: zero unexplained suppressions under
        # horovod_tpu/ — every one is surfaced with a reason string
        rep = lockcheck.check_package(PKG_ROOT)
        assert rep.suppressions, "annotated tree should have suppressions"
        for s in rep.suppressions:
            assert s.reason and s.reason.strip(), str(s)

    def test_hot_classes_are_annotated(self):
        # the ISSUE names the hot classes: their shared state must carry
        # real annotations, not just pass by being unannotated
        rep = lockcheck.check_package(PKG_ROOT)
        assert rep.guarded_attrs >= 30
        assert rep.classes_annotated >= 8

    def test_checkpoint_manager_sweep_is_annotated_and_clean(self):
        # ISSUE 11 satellite: the checkpoint subsystem (written after the
        # PR 7 annotation pass) is inside the lockcheck perimeter — the
        # double-buffer/background-thread state is declared, and a clean
        # result can't come from silently deleted annotations
        rep = lockcheck.check_paths(
            [os.path.join(PKG_ROOT, "checkpoint")],
            root=os.path.dirname(PKG_ROOT))
        assert rep.findings == [], "\n".join(str(f) for f in rep.findings)
        assert rep.classes_annotated >= 1
        assert rep.guarded_attrs >= 4
