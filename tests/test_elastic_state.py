"""Elastic state, run-loop, and notification tests (single-process world).

Mirrors the reference's state contract tests: commit/restore/sync semantics
(common/elastic.py:26-144), the run_fn retry loop (:147-168), and the worker
notification round trip (runner/elastic/worker.py).
"""

import time

import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.common.exceptions import (HorovodInternalError,
                                           HostsUpdatedInterrupt)
from horovod_tpu.elastic import (ObjectState, TPUState, run_fn,
                                 HostUpdateResult)


@pytest.fixture(scope="module", autouse=True)
def init_hvd():
    hvd.init()
    yield


class TestObjectState:
    def test_save_restore(self):
        state = ObjectState(batch=0, epoch=0)
        state.batch = 5
        state.commit()
        state.batch = 9
        state.restore()
        assert state.batch == 5 and state.epoch == 0

    def test_sync_noop_single(self):
        state = ObjectState(batch=3)
        state.sync()
        assert state.batch == 3

    def test_reset_callbacks(self):
        calls = []
        state = ObjectState(batch=0)
        state.register_reset_callbacks([lambda: calls.append(1)])
        state.on_reset()
        assert calls == [1]


class TestTPUState:
    def test_pytree_save_restore(self):
        params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
        state = TPUState(params=params, batch=0)
        state.commit()
        state.params = {"w": jnp.full((4, 4), 7.0), "b": jnp.ones((4,))}
        state.batch = 3
        state.restore()
        np.testing.assert_allclose(np.asarray(state.params["w"]), 1.0)
        assert state.batch == 0

    def test_sync_broadcasts(self):
        params = {"w": jnp.arange(4.0)}
        state = TPUState(params=params, step=2)
        state.sync()
        np.testing.assert_allclose(np.asarray(state.params["w"]),
                                   [0, 1, 2, 3])
        assert state.step == 2

    def test_host_update_interrupt_at_commit(self):
        state = ObjectState(batch=0)
        state.on_hosts_updated(int(time.time() * 1e6),
                               HostUpdateResult.ADDED)
        with pytest.raises(HostsUpdatedInterrupt) as ei:
            state.commit()
        assert ei.value.skip_sync  # additions only → state still valid
        # a mixed update does not skip sync
        state.on_hosts_updated(int(time.time() * 1e6) + 1,
                               HostUpdateResult.MIXED)
        with pytest.raises(HostsUpdatedInterrupt) as ei:
            state.commit()
        assert not ei.value.skip_sync


class TestRunLoop:
    def _state(self):
        class FakeState(ObjectState):
            def __init__(self):
                self.syncs = 0
                self.restores = 0
                super().__init__(batch=0)

            def sync(self):
                self.syncs += 1
                super().sync()

            def restore(self):
                self.restores += 1
                super().restore()
        return FakeState()

    def test_returns_result(self):
        state = self._state()
        resets = []
        wrapped = run_fn(lambda s: "done", lambda: resets.append(1))
        assert wrapped(state) == "done"
        assert state.syncs == 1 and resets == []

    def test_internal_error_restores_and_retries(self):
        state = self._state()
        resets = []
        attempts = []

        def train(s):
            attempts.append(1)
            if len(attempts) == 1:
                raise HorovodInternalError("peer died")
            return "ok"

        wrapped = run_fn(train, lambda: resets.append(1))
        assert wrapped(state) == "ok"
        assert state.restores == 1
        assert len(resets) == 1
        assert state.syncs == 2  # initial + after restore

    def test_jax_runtime_error_restores_and_retries(self):
        """The async eager hot path never blocks inside engine code, so a
        peer crash first surfaces at the user's next value fetch as a raw
        JAX runtime error — the run-loop must treat it like
        HorovodInternalError (restore + reset + retry), or
        dataflow-chained training loses elastic recovery."""
        import jax
        state = self._state()
        resets = []
        attempts = []

        def train(s):
            attempts.append(1)
            if len(attempts) == 1:
                raise jax.errors.JaxRuntimeError(
                    "DATA_LOSS: Connection reset by peer")
            return "ok"

        wrapped = run_fn(train, lambda: resets.append(1))
        assert wrapped(state) == "ok"
        assert state.restores == 1
        assert len(resets) == 1

    def test_persistent_runtime_error_escalates(self):
        """Raw JAX runtime errors are only AMBIGUOUS evidence of a peer
        crash; a deterministic failure (OOM, assert in user jit code) that
        recurs with no intervening commit must escalate after the bounded
        retry budget instead of restore/retry-looping forever (ADVICE r4
        medium — the reference only ever recovers HorovodInternalError)."""
        import jax
        import importlib
        run_mod = importlib.import_module('horovod_tpu.elastic.run')
        state = self._state()
        attempts = []

        def train(s):
            attempts.append(1)
            raise jax.errors.JaxRuntimeError("INTERNAL: deterministic bug")

        budget = run_mod._MAX_RUNTIME_ERROR_RETRIES
        wrapped = run_fn(train, lambda: None)
        with pytest.raises(jax.errors.JaxRuntimeError):
            wrapped(state)
        # initial attempt + the module's retry budget of recoveries
        assert len(attempts) == budget + 1
        assert state.restores == budget

    def test_runtime_error_retry_budget_resets_on_commit(self):
        """A commit between failures proves training advanced — the
        consecutive-failure counter starts over, so transient peer crashes
        spread across a long run never hit the escalation cap."""
        import jax
        import importlib
        run_mod = importlib.import_module('horovod_tpu.elastic.run')
        state = self._state()
        attempts = []
        n_fail = run_mod._MAX_RUNTIME_ERROR_RETRIES * 2  # well past budget

        def train(s):
            attempts.append(1)
            if len(attempts) <= n_fail:
                state.commit()  # progress before every failure
                raise jax.errors.JaxRuntimeError(
                    "DATA_LOSS: Connection reset by peer")
            return "ok"

        wrapped = run_fn(train, lambda: None)
        assert wrapped(state) == "ok"  # every failure recovered
        assert state.restores == n_fail

    def test_hosts_updated_skips_sync_on_add(self):
        state = self._state()
        attempts = []

        def train(s):
            attempts.append(1)
            if len(attempts) == 1:
                raise HostsUpdatedInterrupt(skip_sync=True)
            return "ok"

        wrapped = run_fn(train, lambda: None)
        assert wrapped(state) == "ok"
        assert state.restores == 0
        assert state.syncs == 1  # skip_sync honored


class TestNotificationRoundtrip:
    def test_driver_push_reaches_listener(self):
        from horovod_tpu.elastic.worker import (WorkerNotificationManager,
                                                WorkerNotificationClient)
        from horovod_tpu.runner.http_server import KVStoreServer

        rdv = KVStoreServer()
        rdv.start()
        try:
            mgr = WorkerNotificationManager()
            mgr.init(rendezvous_addr="127.0.0.1", rendezvous_port=rdv.port,
                     rank=0, hostname="127.0.0.1")
            events = []

            class Listener:
                def on_hosts_updated(self, ts, res):
                    events.append((ts, res))

            mgr.register_listener(Listener())
            # the driver reads the advertised address from the KV store
            addr = rdv.snapshot()["worker_addresses"]["0"].decode()
            WorkerNotificationClient(addr).notify_hosts_updated(
                42, HostUpdateResult.REMOVED)
            deadline = time.monotonic() + 5
            while not events and time.monotonic() < deadline:
                time.sleep(0.05)
            assert events == [(42, HostUpdateResult.REMOVED)]
            mgr.shutdown()
        finally:
            rdv.stop()
