"""The exception-propagation & resource-lifecycle analyzer (ISSUE 15
tentpole): every finding class must be detected with file:line on the
known fixtures, the clean fixture must produce zero findings, and the
live ``horovod_tpu/`` tree must be clean with every suppression
carrying its reason and every seam enumerated.
"""

import os
import textwrap

import pytest

from horovod_tpu.analysis import errflow

pytestmark = pytest.mark.lint

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "errflow")
PKG_ROOT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "horovod_tpu")


def _check_fixture(name):
    path = os.path.join(FIXTURES, name)
    rep = errflow.check_paths([path], root=FIXTURES)
    lines = []
    if os.path.isfile(path):
        lines = open(path).read().splitlines()
    return rep, lines


def _line_of(lines, needle, nth=0):
    hits = [i + 1 for i, l in enumerate(lines) if needle in l]
    assert hits, f"fixture drifted: {needle!r} not found"
    return hits[nth]


def _src(s):
    return textwrap.dedent(s)


# ---------------------------------------------------------------------------
# finding classes, asserted by file:line on the fixtures
# ---------------------------------------------------------------------------

class TestViolationClasses:
    def test_swallowed_recovery_error(self):
        rep, lines = _check_fixture("bad_swallow.py")
        got = {(f.check, f.line) for f in rep.findings}
        for marker in ("VIOLATION: swallowed broad except",
                       "VIOLATION: swallowed BaseException",
                       "VIOLATION: swallowed recovery carrier",
                       "VIOLATION: reachable helper swallows"):
            # the finding anchors to the except line directly above the
            # marked handler body
            line = _line_of(lines, marker) - 1
            assert ("swallowed-recovery-error", line) in got, marker
        # reraise/return/escalate/later-raise/import-probe/tail-signal/
        # loop-back-edge and the off-path helper are all sanctioned
        assert len(rep.findings) == 4

    def test_unretried_kv_io(self):
        rep, lines = _check_fixture("bad_kv_io.py")
        got = {(f.check, f.line) for f in rep.findings}
        for marker in ("VIOLATION: deadline-less urlopen",
                       "VIOLATION: deadline-less connect"):
            assert ("unretried-kv-io", _line_of(lines, marker)) in got
        # timeout= and retrying()-wrapped calls are sanctioned
        assert len(rep.findings) == 2

    def test_leak_on_raise(self):
        rep, lines = _check_fixture("bad_leak.py")
        got = {(f.check, f.line) for f in rep.findings}
        for marker in ("VIOLATION: closed only on the success path",
                       "VIOLATION: never closed",
                       "VIOLATION: bind may raise before close",
                       "VIOLATION: started, never joined",
                       "VIOLATION: untracked",
                       "VIOLATION: no method joins"):
            assert ("leak-on-raise", _line_of(lines, marker)) in got, marker
        # JoinedWorker (class-level join) is sanctioned
        assert len(rep.findings) == 6

    def test_silent_error_path(self):
        rep, lines = _check_fixture("bad_silent.py")
        got = {(f.check, f.line) for f in rep.findings}
        for marker in ("VIOLATION: silent degraded mode",
                       "VIOLATION: silent tagged seam"):
            line = _line_of(lines, marker) - 1
            assert ("silent-error-path", line) in got, marker
        # WARNING-logging / counter-incrementing seams and undeclared
        # defs are sanctioned
        assert len(rep.findings) == 2
        # every seam (failpoint-implicit + tagged) is enumerated
        assert {s.func for s in rep.seams} == {
            "silent_failpoint_seam", "silent_tagged_seam",
            "warning_seam", "counted_seam"}

    def test_failpoint_drift_both_directions(self):
        rep, lines = _check_fixture("bad_drift.py")
        got = {(f.check, f.line) for f in rep.findings}
        for marker in ("VIOLATION: dead declaration",
                       "VIOLATION: undeclared name",
                       "VIOLATION: reserved prefix",
                       "VIOLATION: computed name"):
            assert ("failpoint-drift", _line_of(lines, marker)) in got
        assert len(rep.findings) == 4
        assert rep.failpoints_declared == 2
        assert rep.failpoint_sites == 4

    def test_suppression_hygiene(self):
        rep, lines = _check_fixture("bad_suppression.py")
        got = {(f.check, f.line) for f in rep.findings}
        assert ("bad-suppression",
                _line_of(lines, "errflow: ignore[]")) in got
        assert ("stale-suppression",
                _line_of(lines, "stale: the code this excused")) in got
        assert len(rep.findings) == 2
        assert len(rep.suppressions) == 1
        s = rep.suppressions[0]
        assert s.check == "swallowed-recovery-error"
        assert "reasoned" in s.reason

    def test_cross_file_propagation(self):
        """The recovery footprint resolves across files: run_fn in
        runloop.py reaches the swallow in helper.py; the unreached def
        is not flagged."""
        rep, _ = _check_fixture("xfile")
        helper = open(os.path.join(FIXTURES, "xfile",
                                   "helper.py")).read().splitlines()
        assert len(rep.findings) == 1
        f = rep.findings[0]
        assert f.check == "swallowed-recovery-error"
        assert f.file == os.path.join("xfile", "helper.py")
        assert f.line == _line_of(helper, "VIOLATION: cross-file swallow") - 1

    def test_clean_fixture_zero_findings(self):
        rep, _ = _check_fixture("clean.py")
        assert rep.findings == []
        assert rep.suppressions == []
        # the observable seam is still discovered and enumerated
        assert [s.func for s in rep.seams] == ["observable_publish"]


# ---------------------------------------------------------------------------
# convention units (in-memory sources)
# ---------------------------------------------------------------------------

class TestConventions:
    def test_trailing_suppression_does_not_bleed(self):
        """A trailing ignore covers its own line only; the next line's
        finding survives."""
        rep = errflow.check_source(_src("""
            def synchronize(a, b):
                try:
                    a()
                except Exception:  # errflow: ignore[first swallow is deliberate]
                    a.done = True
                try:
                    b()
                except Exception:
                    b.done = True
        """))
        assert len(rep.findings) == 1
        assert rep.findings[0].line == 9
        assert len(rep.suppressions) == 1

    def test_standalone_suppression_covers_line_below(self):
        rep = errflow.check_source(_src("""
            def synchronize(a):
                try:
                    a()
                # errflow: ignore[deliberate]
                except Exception:
                    a.done = True
        """))
        assert rep.findings == []
        assert len(rep.suppressions) == 1

    def test_seam_tag_standalone_above_def(self):
        rep = errflow.check_source(_src("""
            # errflow: seam[declared degraded path]
            def push(kv, v):
                try:
                    kv.put(v)
                except Exception:
                    v.dropped = True
        """))
        assert [f.check for f in rep.findings] == ["silent-error-path"]
        assert rep.seams[0].how == "declared degraded path"

    def test_handler_return_and_raise_propagate(self):
        rep = errflow.check_source(_src("""
            def synchronize(a, b):
                try:
                    a()
                except Exception:
                    return None
                try:
                    b()
                except Exception as e:
                    raise RuntimeError("wrapped") from e
        """))
        assert rep.findings == []

    def test_bound_error_raised_later_propagates(self):
        rep = errflow.check_source(_src("""
            def synchronize(work):
                last = None
                for _ in range(3):
                    try:
                        return work()
                    except Exception as e:
                        last = e
                raise last
        """))
        assert rep.findings == []

    def test_tail_return_after_try_propagates(self):
        rep = errflow.check_source(_src("""
            def synchronize(work):
                ok = True
                try:
                    work()
                except Exception:
                    ok = False
                return ok
        """))
        assert rep.findings == []

    def test_loop_back_edge_raise_propagates(self):
        """The long-poll idiom: the deadline raise at the TOP of the
        while body is reachable from the handler via the back edge."""
        rep = errflow.check_source(_src("""
            def synchronize(work, expired):
                while True:
                    if expired():
                        raise TimeoutError("deadline")
                    try:
                        return work()
                    except Exception as e:
                        work.last = e
        """))
        assert rep.findings == []

    def test_retry_loop_guarded_raise_is_no_signal(self):
        """A raise INSIDE the try body does not exempt the broad handler
        around it in a loop — the handler re-swallows it every
        iteration (infinite silent retry, the exact bug class)."""
        rep = errflow.check_source(_src("""
            def _dispatch(work):
                while True:
                    try:
                        if work.bad:
                            raise RuntimeError("fault")
                        work()
                    except Exception:
                        pass
        """))
        assert [f.check for f in rep.findings] == ["swallowed-recovery-error"]

    def test_sibling_narrow_clause_does_not_vouch_for_broad(self):
        """A re-raise in a sibling ``except ValueError`` runs only for
        ValueErrors — it cannot excuse the broad swallow next to it."""
        rep = errflow.check_source(_src("""
            def _dispatch(work):
                while True:
                    try:
                        work()
                    except ValueError:
                        raise
                    except Exception:
                        pass
        """))
        assert [f.check for f in rep.findings] == ["swallowed-recovery-error"]

    def test_positional_timeout_is_deadlined(self):
        """``create_connection(addr, 5.0)`` — timeout as the documented
        second positional — is a deadlined call; the same call with the
        address alone is not."""
        rep = errflow.check_source(_src("""
            import socket

            def deadlined(addr):
                return socket.create_connection(addr, 5.0)

            def bare(addr):
                return socket.create_connection(addr)
        """))
        assert [(f.check, f.func) for f in rep.findings] == [
            ("unretried-kv-io", "bare")]

    def test_import_probe_exempt(self):
        rep = errflow.check_source(_src("""
            def synchronize(errs):
                try:
                    import optional_dep
                    errs.append(optional_dep.Error)
                except Exception:
                    pass
        """))
        assert rep.findings == []

    def test_nested_def_handler_checked_in_own_context(self):
        """A later raise in the OUTER def does not excuse a swallow
        inside a nested closure."""
        rep = errflow.check_source(_src("""
            def synchronize(work):
                def inner():
                    try:
                        work()
                    except Exception:
                        work.done = True
                inner()
                raise RuntimeError("outer tail")
        """))
        assert [f.check for f in rep.findings] == ["swallowed-recovery-error"]

    def test_narrow_except_not_flagged_on_recovery_path(self):
        rep = errflow.check_source(_src("""
            def synchronize(work):
                try:
                    work()
                except OSError:
                    work.done = True
        """))
        assert rep.findings == []

    def test_retrying_exemption_for_io(self):
        rep = errflow.check_source(_src("""
            import urllib.request
            from horovod_tpu.common.retry import retrying

            def fetch(url):
                def _attempt():
                    return urllib.request.urlopen(url)
                return retrying(_attempt, attempts=2)
        """))
        assert rep.findings == []

    def test_with_managed_resources_clean(self):
        rep = errflow.check_source(_src("""
            import socket

            def read(path, addr):
                with open(path) as f, \\
                        socket.create_connection(addr, timeout=1) as s:
                    s.send(f.read())
        """))
        assert rep.findings == []

    def test_thread_joined_by_sibling_method_via_base_class(self):
        """Release methods merge over same-file bases: a subclass of a
        joining server is covered."""
        rep = errflow.check_source(_src("""
            import threading

            class Server:
                def stop(self):
                    self._thread.join(timeout=5)

            class KVServer(Server):
                def start(self, target):
                    self._thread = threading.Thread(target=target)
                    self._thread.start()
        """))
        assert rep.findings == []

    def test_parse_error_reported_not_crash(self):
        rep = errflow.check_source("def broken(:\n")
        assert [f.check for f in rep.findings] == ["parse-error"]

    def test_check_sources_cross_file(self):
        rep = errflow.check_sources({
            "a.py": _src("""
                def _dispatch(x):
                    helper(x)
            """),
            "b.py": _src("""
                def helper(x):
                    try:
                        x()
                    except Exception:
                        x.done = True
            """),
        })
        assert len(rep.findings) == 1
        assert rep.findings[0].file == "b.py"

    def test_no_propagate_names_block_reachability(self):
        """A bare .run() call edge must not drag every def named run
        onto the recovery path."""
        rep = errflow.check_sources({
            "a.py": _src("""
                def _dispatch(x):
                    x.run()
            """),
            "b.py": _src("""
                def run(x):
                    try:
                        x()
                    except Exception:
                        x.done = True
            """),
        })
        assert rep.findings == []


# ---------------------------------------------------------------------------
# live-tree keep-honest floors
# ---------------------------------------------------------------------------

class TestLiveTree:
    @pytest.fixture(scope="class")
    def live(self):
        return errflow.check_package(PKG_ROOT)

    def test_live_tree_clean(self, live):
        assert live.findings == [], "\n".join(str(f) for f in live.findings)

    def test_scan_coverage_floors(self, live):
        """A gutted collector cannot go green: the scan must actually
        cover the tree (counts at HEAD: 83 files, ~1160 defs, ~310
        recovery-path defs, ~170 handlers, 24 seams)."""
        assert live.files >= 60
        assert live.defs >= 900
        assert live.recovery_defs >= 150
        assert live.handlers >= 120
        assert len(live.seams) >= 15
        assert live.failpoints_declared >= 15
        assert live.failpoint_sites >= 20

    def test_all_suppressions_reasoned(self, live):
        assert live.suppressions, \
            "the annotated tree is expected to carry suppressions"
        for s in live.suppressions:
            assert s.reason and s.reason.strip(), s.to_dict()

    def test_known_fixed_violations_stay_fixed(self, live):
        """The ISSUE 15 sweep fixes: the cycle-loop join, the task-
        service join, the data-loader join, and the find_free_port
        socket lifecycle must not regress (they would reappear as
        findings, caught by test_live_tree_clean — this pins the
        specific files so a suppression can't hide a regression)."""
        for rel in ("horovod_tpu/core/engine.py",
                    "horovod_tpu/runner/http_server.py",
                    "horovod_tpu/data.py"):
            leaks = [s for s in live.suppressions
                     if s.file == rel and s.check == "leak-on-raise"]
            assert not leaks, f"{rel}: fixed leak re-suppressed: {leaks}"
        assert "horovod_tpu/runner/service.py" not in {
            s.file for s in live.suppressions
            if "self._thread" in s.message}

    def test_report_json_round_trip(self, live):
        d = live.to_dict()
        assert d["ok"] is True
        assert isinstance(d["suppressions"], list)
        for s in d["suppressions"]:
            assert {"check", "file", "line", "reason"} <= set(s)
        for s in d["seams"]:
            assert {"file", "line", "func", "how"} <= set(s)
