"""Flagship transformer: dp/sp/tp-sharded loss and train step must match the
single-device computation — the SPMD analog of the reference's rule that
distributed training reproduce serial numerics."""

import dataclasses

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from horovod_tpu.models import transformer as tfm


CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                            d_ff=64, max_seq=32, dtype=jnp.float32)


def _data(bsz=4, seq=16, seed=0):
    rng = np.random.RandomState(seed)
    inputs = rng.randint(0, CFG.vocab_size, size=(bsz, seq)).astype(np.int32)
    targets = rng.randint(0, CFG.vocab_size, size=(bsz, seq)).astype(np.int32)
    return inputs, targets


def _single_device_loss(params, inputs, targets):
    total, count, _aux = tfm._local_loss(params, jnp.asarray(inputs),
                                         jnp.asarray(targets), CFG)
    return total / count


@pytest.mark.parametrize("shape", [(2, 2, 2), (8, 1, 1), (1, 4, 2)])
def test_spmd_loss_matches_single_device(shape):
    d, s, t = shape
    devs = np.array(jax.devices()[:d * s * t]).reshape(d, s, t)
    mesh = Mesh(devs, (tfm.DATA_AXIS, tfm.SEQ_AXIS, tfm.TENSOR_AXIS))
    params = tfm.init_params(jax.random.PRNGKey(0), CFG)
    inputs, targets = _data(bsz=8)

    ref = float(_single_device_loss(params, inputs, targets))

    loss_fn = tfm.make_spmd_loss(mesh, CFG)
    sharded_params = tfm.shard_params(params, mesh, CFG)
    tok_sh = NamedSharding(mesh, P(tfm.DATA_AXIS, tfm.SEQ_AXIS))
    out = float(jax.jit(loss_fn)(sharded_params, jax.device_put(inputs, tok_sh),
                                 jax.device_put(targets, tok_sh)))
    assert abs(out - ref) / abs(ref) < 1e-4, (out, ref)


def test_spmd_loss_zigzag_layout_matches():
    """sp_layout='zigzag' (causally load-balanced ring): feeding the
    zigzag-permuted tokens/targets must give the SAME loss — the per-token
    loss mean is permutation-invariant, and the lean LM has no positional
    encoding, so only the ring schedule changes."""
    from horovod_tpu.parallel.ring_attention import zigzag_indices
    d, s, t = 1, 4, 1
    devs = np.array(jax.devices()[:d * s * t]).reshape(d, s, t)
    mesh = Mesh(devs, (tfm.DATA_AXIS, tfm.SEQ_AXIS, tfm.TENSOR_AXIS))
    cfg = dataclasses.replace(CFG, sp_layout="zigzag")
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    inputs, targets = _data(bsz=4, seq=16)
    ref = float(_single_device_loss(params, inputs, targets))

    idx, _ = zigzag_indices(16, s)
    loss_fn = tfm.make_spmd_loss(mesh, cfg)
    sharded_params = tfm.shard_params(params, mesh, cfg)
    tok_sh = NamedSharding(mesh, P(tfm.DATA_AXIS, tfm.SEQ_AXIS))
    zi = jnp.take(jnp.asarray(inputs), idx, axis=1)
    zt = jnp.take(jnp.asarray(targets), idx, axis=1)
    out = float(jax.jit(loss_fn)(sharded_params,
                                 jax.device_put(zi, tok_sh),
                                 jax.device_put(zt, tok_sh)))
    assert abs(out - ref) / abs(ref) < 1e-4, (out, ref)


def test_spmd_train_step_decreases_loss_and_matches_dp1():
    mesh = Mesh(np.array(jax.devices()).reshape(2, 2, 2),
                (tfm.DATA_AXIS, tfm.SEQ_AXIS, tfm.TENSOR_AXIS))
    params = tfm.init_params(jax.random.PRNGKey(1), CFG)
    opt = optax.sgd(0.1)
    inputs, targets = _data(bsz=4, seq=16, seed=2)

    # Single-device reference: 2 full-batch SGD steps.
    ref_params = params
    ref_state = opt.init(ref_params)
    losses_ref = []
    for _ in range(2):
        loss, grads = jax.value_and_grad(
            lambda p: _single_device_loss(p, inputs, targets))(ref_params)
        updates, ref_state = opt.update(grads, ref_state, ref_params)
        ref_params = optax.apply_updates(ref_params, updates)
        losses_ref.append(float(loss))

    # SPMD: same total batch split over the mesh.
    step = tfm.make_train_step(mesh, CFG, opt)
    sp = tfm.shard_params(params, mesh, CFG)
    st = opt.init(sp)
    tok_sh = NamedSharding(mesh, P(tfm.DATA_AXIS, tfm.SEQ_AXIS))
    gi, gt = jax.device_put(inputs, tok_sh), jax.device_put(targets, tok_sh)
    losses = []
    for _ in range(2):
        sp, st, loss = step(sp, st, gi, gt)
        losses.append(float(loss))

    assert losses[1] < losses[0], losses
    np.testing.assert_allclose(losses, losses_ref, rtol=1e-3)


def test_ulysses_attention_variant_matches_ring():
    """attention='ulysses' computes the same exact attention as 'ring': the
    SPMD loss must be identical for identical params/data."""
    devs = np.array(jax.devices()).reshape(2, 2, 2)
    mesh = jax.sharding.Mesh(devs, (tfm.DATA_AXIS, tfm.SEQ_AXIS,
                                    tfm.TENSOR_AXIS))
    losses = {}
    for attn in ("ring", "ulysses"):
        cfg = dataclasses.replace(CFG, attention=attn)
        params = tfm.shard_params(
            tfm.init_params(jax.random.PRNGKey(0), cfg), mesh, cfg)
        inputs, targets = _data(4, 16)
        loss_fn = jax.jit(tfm.make_spmd_loss(mesh, cfg))
        tok_sh = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(tfm.DATA_AXIS, tfm.SEQ_AXIS))
        losses[attn] = float(loss_fn(
            params, jax.device_put(jnp.asarray(inputs), tok_sh),
            jax.device_put(jnp.asarray(targets), tok_sh)))
    np.testing.assert_allclose(losses["ring"], losses["ulysses"], rtol=2e-5)


@pytest.mark.skipif(
    tuple(int(p) for p in jax.__version__.split(".")[:2]) < (0, 5),
    reason="shard_map raises _SpecError for the MoE train step's out_specs "
           "on the container's jax 0.4.x (pre-existing since PR 6's seed "
           "audit; passes on jax >= 0.5)")
def test_moe_variant_trains_and_matches_across_meshes():
    """use_moe=True: the train step runs on a (data, seq, tensor=expert)
    mesh; the SPMD loss equals the single-device loss for the same params
    (expert sharding must not change routing results)."""
    import optax
    cfg = dataclasses.replace(CFG, use_moe=True, n_experts=4,
                              moe_capacity_factor=4.0)
    params_full = tfm.init_params(jax.random.PRNGKey(1), cfg)
    inputs, targets = _data(4, 16, seed=5)
    # single-device reference (no shard_map)
    total, count, aux = tfm._local_loss(params_full, jnp.asarray(inputs),
                                        jnp.asarray(targets), cfg)
    ref = float(total / count + cfg.moe_aux_weight * aux)

    devs = np.array(jax.devices()).reshape(2, 2, 2)
    mesh = jax.sharding.Mesh(devs, (tfm.DATA_AXIS, tfm.SEQ_AXIS,
                                    tfm.TENSOR_AXIS))
    params = tfm.shard_params(params_full, mesh, cfg)
    tok_sh = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(tfm.DATA_AXIS, tfm.SEQ_AXIS))
    ti = jax.device_put(jnp.asarray(inputs), tok_sh)
    tt = jax.device_put(jnp.asarray(targets), tok_sh)
    loss_fn = jax.jit(tfm.make_spmd_loss(mesh, cfg))
    np.testing.assert_allclose(float(loss_fn(params, ti, tt)), ref,
                               rtol=5e-4)
    # and a full train step updates the routers/experts with finite values
    # (snapshot before the step: donate_argnums consumes the input buffers)
    router_before = np.array(np.asarray(params["layers"]["router"]))
    opt = optax.adam(1e-3)
    step = tfm.make_train_step(mesh, cfg, opt)
    p2, _, loss = step(params, opt.init(params), ti, tt)
    assert np.isfinite(float(loss))
    delta = np.abs(np.asarray(p2["layers"]["router"]) - router_before)
    assert delta.sum() > 0  # router learned


def test_moe_pad_tokens_do_not_skew_results():
    """Per-shard token count not divisible by tensor_size: pad rows must not
    route, consume capacity, or skew the aux loss — loss still matches the
    single-device reference (review r2 scenario)."""
    cfg = dataclasses.replace(CFG, use_moe=True, n_experts=4,
                              moe_capacity_factor=8.0)
    params_full = tfm.init_params(jax.random.PRNGKey(3), cfg)
    rng = np.random.RandomState(7)
    inputs = rng.randint(0, CFG.vocab_size, size=(3, 6)).astype(np.int32)
    targets = rng.randint(0, CFG.vocab_size, size=(3, 6)).astype(np.int32)
    total, count, aux = tfm._local_loss(params_full, jnp.asarray(inputs),
                                        jnp.asarray(targets), cfg)
    ref = float(total / count + cfg.moe_aux_weight * aux)

    # 18 tokens per shard over tensor=4 -> pad of 2
    devs = np.array(jax.devices()[:4]).reshape(1, 1, 4)
    mesh = jax.sharding.Mesh(devs, (tfm.DATA_AXIS, tfm.SEQ_AXIS,
                                    tfm.TENSOR_AXIS))
    params = tfm.shard_params(params_full, mesh, cfg)
    tok_sh = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(tfm.DATA_AXIS, tfm.SEQ_AXIS))
    loss = float(jax.jit(tfm.make_spmd_loss(mesh, cfg))(
        params, jax.device_put(jnp.asarray(inputs), tok_sh),
        jax.device_put(jnp.asarray(targets), tok_sh)))
    np.testing.assert_allclose(loss, ref, rtol=5e-4)


def test_flash_attention_fallback_and_lean_loss():
    """attention="flash" falls back to the materialized kernel off-TPU, and
    lean_lm_loss matches the log_softmax formulation (fp32 config)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from horovod_tpu.models.transformer import (TransformerConfig,
                                                init_params, _local_loss,
                                                lean_lm_loss)

    cfg = TransformerConfig(vocab_size=128, d_model=32, n_heads=4, n_layers=2,
                            d_ff=64, max_seq=16, dtype=jnp.float32,
                            attention="flash")
    params = init_params(jax.random.PRNGKey(0), cfg)
    tok = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, 16)))
    tgt = jnp.asarray(np.random.RandomState(1).randint(0, 128, (2, 16)))
    lean = float(lean_lm_loss(params, tok, tgt, cfg))
    total, count, _ = _local_loss(params, tok, tgt, cfg)
    ref = float(total) / count
    assert abs(lean - ref) < 1e-4, (lean, ref)

    # flash config == default config numerics on the fallback path
    cfg_ref = TransformerConfig(vocab_size=128, d_model=32, n_heads=4,
                                n_layers=2, d_ff=64, max_seq=16,
                                dtype=jnp.float32)
    total2, _, _ = _local_loss(params, tok, tgt, cfg_ref)
    assert abs(float(total) - float(total2)) < 1e-5


def test_multislice_mesh_flagship_step():
    """The flagship train step compiles and runs over a DCN-aware
    (data@DCN, seq+tensor@ICI) multislice_mesh — the multi-slice pod layout
    (single-slice fallback path on the CPU world; real pods use
    create_hybrid_device_mesh with the same axis semantics)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from horovod_tpu.parallel.mesh import multislice_mesh
    from horovod_tpu.models.transformer import (TransformerConfig,
                                                init_params, make_train_step,
                                                shard_params)

    mesh = multislice_mesh({"data": 2}, {"seq": 2, "tensor": 2})
    assert mesh.axis_names == ("data", "seq", "tensor")
    assert mesh.devices.shape == (2, 2, 2)
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                            d_ff=64, max_seq=16, dtype=jnp.float32)
    params = shard_params(init_params(jax.random.PRNGKey(0), cfg), mesh, cfg)
    opt = optax.sgd(0.01)
    step = make_train_step(mesh, cfg, opt)
    tok = jax.device_put(jnp.zeros((4, 16), jnp.int32),
                         NamedSharding(mesh, P("data", "seq")))
    p2, o2, loss = step(params, opt.init(params), tok, tok)
    assert np.isfinite(float(loss))


def test_splash_gating_and_kernel_construction():
    """The splash gating/block-size logic is pure Python (mask + BlockSizes
    validation run in numpy) and must handle every T the gate admits —
    including odd multiples of 1024 where kv-block 2048 doesn't divide T
    (review finding: T=3072 crashed make_splash_mha)."""
    import pytest
    pytest.importorskip(
        "jax.experimental.pallas.ops.tpu.splash_attention")
    from horovod_tpu.parallel.flash_attention import (_splash_kernel,
                                                      _splash_ok)
    sq = (1, 4, 1024, 128)
    assert _splash_ok(sq, sq)
    assert _splash_ok((1, 4, 3072, 128), (1, 4, 3072, 128))
    assert not _splash_ok((1, 4, 512, 128), (1, 4, 512, 128))   # too short
    assert not _splash_ok((1, 4, 1536, 128), (1, 4, 1536, 128))  # not /1024
    assert not _splash_ok((1, 4, 2048, 64), (1, 4, 2048, 64))   # d not 128
    assert not _splash_ok(sq, (1, 4, 2048, 128))  # rectangular q/kv
    for t in (1024, 2048, 3072):
        for causal in (True, False):
            k = _splash_kernel(2, t, causal)   # construction validates blocks
            assert k is not None
    _splash_kernel.cache_clear()


def test_remat_matches_no_remat():
    """VERDICT r3 item 4: remat changes memory, never numerics — loss and
    grads under remat='block'/'attention' match remat='none' exactly (same
    program modulo recompute), on the single-shard AND the SPMD path."""
    params = tfm.init_params(jax.random.PRNGKey(3), CFG)
    inputs, targets = _data(bsz=2, seq=16, seed=4)

    def loss_of(cfg):
        def f(p):
            total, count, _aux = tfm._local_loss(
                p, jnp.asarray(inputs), jnp.asarray(targets), cfg)
            return total / count
        return jax.jit(jax.value_and_grad(f))

    base_l, base_g = loss_of(CFG)(params)
    for mode in ("block", "attention"):
        cfg = dataclasses.replace(CFG, remat=mode)
        l, g = loss_of(cfg)(params)
        np.testing.assert_allclose(float(l), float(base_l), rtol=1e-6)
        for a, b in zip(jax.tree_util.tree_leaves(g),
                        jax.tree_util.tree_leaves(base_g)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    # SPMD path with remat compiles and matches too (ring attention's custom
    # VJP must survive jax.checkpoint's recompute)
    mesh = Mesh(np.array(jax.devices()).reshape(2, 2, 2),
                (tfm.DATA_AXIS, tfm.SEQ_AXIS, tfm.TENSOR_AXIS))
    tok_sh = NamedSharding(mesh, P(tfm.DATA_AXIS, tfm.SEQ_AXIS))
    sp = tfm.shard_params(params, mesh, CFG)
    gi = jax.device_put(inputs, tok_sh)
    gt = jax.device_put(targets, tok_sh)
    ref = float(jax.jit(tfm.make_spmd_loss(mesh, CFG))(sp, gi, gt))
    cfg = dataclasses.replace(CFG, remat="block")
    out = float(jax.jit(tfm.make_spmd_loss(mesh, cfg))(sp, gi, gt))
    assert abs(out - ref) / abs(ref) < 1e-5, (out, ref)


def test_remat_unknown_mode_raises():
    cfg = dataclasses.replace(CFG, remat="everything")
    params = tfm.init_params(jax.random.PRNGKey(0), CFG)
    with pytest.raises(ValueError, match="remat"):
        tfm.forward_block(params, jnp.zeros((1, 8), jnp.int32), cfg)
