"""Top-level eager API tests in a size-1 world (single process).

Multi-process eager semantics are covered by the launcher integration tests
(tests/test_launcher.py), matching the reference's split between in-process
unit tests and under-mpirun tests.
"""

import numpy as np
import pytest
import jax.numpy as jnp

import horovod_tpu as hvd


@pytest.fixture(scope="module", autouse=True)
def init_hvd():
    hvd.init()
    yield


def test_topology():
    assert hvd.is_initialized()
    assert hvd.size() == 1
    assert hvd.rank() == 0
    assert hvd.local_rank() == 0
    assert hvd.local_size() == 1
    assert hvd.cross_size() == 1
    assert hvd.is_homogeneous()
    assert hvd.xla_built() and hvd.xla_enabled()
    assert not hvd.mpi_built() and not hvd.nccl_built() and not hvd.gloo_built()


def test_allreduce_identity_size1():
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    out = np.asarray(hvd.allreduce(x, name="t1", op=hvd.Sum))
    np.testing.assert_array_equal(out, x)
    out2 = np.asarray(hvd.allreduce(x, name="t2", op=hvd.Average))
    np.testing.assert_array_equal(out2, x)


def test_async_handle_poll_synchronize():
    x = np.ones((5,), np.float32)
    h = hvd.allreduce_async(x, name="async1")
    res = hvd.synchronize(h)
    assert hvd.poll(h)
    np.testing.assert_array_equal(np.asarray(res), x)


def test_legacy_average_arg():
    x = np.ones((4,), np.float32)
    out = np.asarray(hvd.allreduce(x, name="avg_legacy", average=True))
    np.testing.assert_array_equal(out, x)
    with pytest.raises(ValueError):
        hvd.allreduce(x, name="both_args", op=hvd.Sum, average=True)


def test_duplicate_name_rejected():
    # Deterministic version of the reference's duplicate-name check
    # (common.h:163-166): plant a genuinely in-flight handle, then re-submit.
    from horovod_tpu.core.state import global_state
    from horovod_tpu.core.engine import Handle

    eng = global_state().engine

    class NeverReady:
        def is_ready(self):
            return False

        def block_until_ready(self):
            return self

    h = Handle("dup", [NeverReady()], lambda gs: None, eng)
    eng._track("dup", h)
    try:
        with pytest.raises(hvd.DuplicateNameError):
            hvd.allreduce_async(np.ones((4,), np.float32), name="dup")
    finally:
        eng._on_complete(h)


def test_completed_name_reusable():
    # Fire-and-forget reuse: once the device op finishes, the same name must be
    # accepted again without an explicit synchronize.
    x = np.ones((8,), np.float32)
    h1 = hvd.allreduce_async(x, name="reuse")
    for g in h1._garrs:
        g.block_until_ready()  # device-side completion only; no user poll
    h2 = hvd.allreduce_async(x, name="reuse")
    np.testing.assert_array_equal(np.asarray(hvd.synchronize(h2)), x)
    hvd.synchronize(h1)


def test_allgather_size1():
    x = np.random.randn(3, 2).astype(np.float32)
    out = np.asarray(hvd.allgather(x, name="ag1"))
    np.testing.assert_array_equal(out, x)


def test_broadcast_size1():
    x = np.random.randn(4).astype(np.float32)
    out = np.asarray(hvd.broadcast(x, root_rank=0, name="bc1"))
    np.testing.assert_array_equal(out, x)


def test_alltoall_size1():
    x = np.arange(6, dtype=np.float32)
    # No splits → tensor only (drop-in parity with torch/mpi_ops.py alltoall).
    out = hvd.alltoall(x, name="a2a1")
    np.testing.assert_array_equal(np.asarray(out), x)
    # With splits → (tensor, received_splits).
    out2, splits = hvd.alltoall(x, splits=[6], name="a2a2")
    np.testing.assert_array_equal(np.asarray(out2), x)
    assert np.asarray(splits).tolist() == [6]


def test_integer_average_rejected():
    with pytest.raises(ValueError, match="integer"):
        hvd.allreduce(np.ones((4,), np.int32), name="int_avg", op=hvd.Average)


def test_reducescatter_bad_op_rejected():
    with pytest.raises(ValueError, match="Sum and Average"):
        hvd.reducescatter(np.ones((4,), np.float32), name="rs_bad", op=hvd.Min)


def test_adasum_eager_size1():
    # size-1 world: Adasum of a single contribution is the identity.
    x = np.random.randn(16).astype(np.float32)
    out = np.asarray(hvd.allreduce(x, name="adasum1", op=hvd.Adasum))
    np.testing.assert_allclose(out, x, rtol=1e-6)
    outs = hvd.grouped_allreduce([x, x * 2], name="adasum_grp", op=hvd.Adasum)
    np.testing.assert_allclose(np.asarray(outs[1]), x * 2, rtol=1e-6)


def test_grouped_allreduce():
    ts = [np.ones((4,), np.float32), np.full((3,), 2.0, np.float32),
          np.arange(5, dtype=np.float32)]
    outs = hvd.grouped_allreduce(ts, name="grp1")
    assert len(outs) == 3
    for t, o in zip(ts, outs):
        np.testing.assert_array_equal(np.asarray(o), t)


def test_barrier_and_join():
    hvd.barrier()
    assert hvd.join() == hvd.size() - 1


def test_broadcast_object_and_parameters():
    obj = {"a": 1, "b": [1, 2, 3]}
    assert hvd.broadcast_object(obj) == obj
    params = {"w": jnp.ones((3, 3)), "b": jnp.zeros((3,))}
    out = hvd.broadcast_parameters(params, root_rank=0)
    np.testing.assert_array_equal(np.asarray(out["w"]), np.ones((3, 3)))


def test_allgather_object():
    assert hvd.allgather_object({"r": 0}) == [{"r": 0}]


def test_allreduce_sparse_single_process():
    """Sparse row-indexed reduction (reference IndexedSlices fallback,
    tensorflow/__init__.py:52-131): duplicates combine, result matches the
    dense allreduce."""
    import numpy as np
    import horovod_tpu as hvd
    hvd.init()
    idx = np.array([3, 1, 3, 7])
    val = np.array([[1.0, 1.0], [2.0, 2.0], [10.0, 10.0], [4.0, 4.0]],
                   np.float32)
    u, c = hvd.allreduce_sparse(idx, val, n_rows=10, average=False)
    np.testing.assert_array_equal(u, [1, 3, 7])
    np.testing.assert_allclose(c, [[2, 2], [11, 11], [4, 4]])
    # equivalence with the dense path
    dense = np.zeros((10, 2), np.float32)
    np.add.at(dense, idx, val)
    dense_out = np.asarray(hvd.allreduce(dense, name="sparse.ref",
                                         op=hvd.Sum))
    rebuilt = np.zeros_like(dense)
    rebuilt[u] = c
    np.testing.assert_allclose(rebuilt, dense_out)
    import pytest
    with pytest.raises(ValueError):
        hvd.allreduce_sparse(np.array([11]), np.ones((1, 2)), n_rows=10)
