"""Metrics registry + cluster telemetry tests (ISSUE 3): registry
semantics (counter monotonicity, log2 histogram bucketing,
snapshot-is-copy), Prometheus text rendering, the KVStoreServer
``GET /metrics`` aggregation round-trip, the metric-namespace lint tool,
and an np=2 end-to-end scrape whose numbers reconcile with each worker's
``hvd.metrics_snapshot()``."""

import importlib.util
import json
import os
import re
import subprocess
import sys
import urllib.request

import numpy as np
import pytest

from horovod_tpu import metrics as hmetrics
from horovod_tpu.metrics import (METRIC_SPECS, Registry, _NOOP,
                                 render_prometheus,
                                 render_prometheus_cluster)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$")
_LABEL_PAIR_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _parse_prom(text):
    """Minimal Prometheus text parser: returns (samples, type_lines) where
    samples is a list of (name, labels_dict, value). Any malformed line
    fails the parse (the 'Prometheus-parseable' acceptance bar)."""
    samples, type_lines = [], []
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] == "TYPE":
                type_lines.append((parts[2], parts[3]))
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        name, labelstr, val = m.groups()
        labels = dict(_LABEL_PAIR_RE.findall(labelstr)) if labelstr else {}
        v = float("inf") if val == "+Inf" else float(val)
        samples.append((name, labels, v))
    return samples, type_lines


def _tot(snap, name, section="counters"):
    ent = snap.get(section, {}).get(name)
    if not ent:
        return 0.0
    return sum(v for _, v in ent["values"])


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_counter_monotonic(self):
        reg = Registry()
        c = reg.counter("hvd_tpu_test_a_total", help="h")
        c.inc()
        c.inc(4.0)
        assert c.value() == 5.0
        with pytest.raises(ValueError):
            c.inc(-1.0)
        assert c.value() == 5.0

    def test_counter_labels_independent(self):
        reg = Registry()
        c = reg.counter("hvd_tpu_test_b_total", help="h")
        c.inc(3, kind="allreduce", dtype="float32")
        c.inc(5, kind="allgather", dtype="float32")
        assert c.value(kind="allreduce", dtype="float32") == 3
        assert c.value(kind="allgather", dtype="float32") == 5
        assert c.total() == 8

    def test_gauge(self):
        reg = Registry()
        g = reg.gauge("hvd_tpu_test_g", help="h")
        g.set(7.0)
        g.set(2.5)
        assert g.value() == 2.5
        g.inc(0.5)
        assert g.value() == 3.0

    def test_histogram_log2_bucketing(self):
        reg = Registry()
        h = reg.histogram("hvd_tpu_test_h_seconds", help="h",
                          min_exp=-3, max_exp=3)
        assert h.bounds == [0.125, 0.25, 0.5, 1.0, 2.0, 4.0, 8.0]
        for v in (0.3, 5.0, 100.0):
            h.observe(v, kind="x")
        [(labels, ent)] = h._snap()
        assert labels == {"kind": "x"}
        assert ent["count"] == 3
        assert ent["sum"] == pytest.approx(105.3)
        buckets = dict((str(le), c) for le, c in ent["buckets"])
        # 0.3 -> le=0.5; 5.0 -> le=8; 100 -> only +Inf (cumulative counts)
        assert buckets["0.5"] == 1
        assert buckets["8.0"] == 2
        assert buckets["+Inf"] == 3

    def test_snapshot_is_copy(self):
        reg = Registry()
        c = reg.counter("hvd_tpu_test_c_total", help="h")
        c.inc(2, kind="k")
        snap = reg.snapshot()
        snap["counters"]["hvd_tpu_test_c_total"]["values"][0][1] = 999
        snap["counters"]["hvd_tpu_test_c_total"]["values"][0][0]["kind"] = "x"
        fresh = reg.snapshot()
        assert fresh["counters"]["hvd_tpu_test_c_total"]["values"] == \
            [[{"kind": "k"}, 2.0]]

    def test_name_and_help_validation(self):
        reg = Registry()
        with pytest.raises(ValueError, match="must match"):
            reg.counter("bad-name", help="h")
        with pytest.raises(ValueError, match="must match"):
            reg.counter("not_hvd_prefixed_total", help="h")
        with pytest.raises(ValueError, match="help"):
            reg.counter("hvd_tpu_undeclared_total")   # no spec, no help
        # declared names resolve their help from METRIC_SPECS
        c = reg.counter("hvd_tpu_dispatches_total")
        assert c.help == METRIC_SPECS["hvd_tpu_dispatches_total"][1]

    def test_type_clash_rejected(self):
        reg = Registry()
        reg.counter("hvd_tpu_test_d_total", help="h")
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("hvd_tpu_test_d_total", help="h")

    def test_event_log(self):
        reg = Registry()
        ev = reg.event_log("hvd_tpu_test_events", help="h", maxlen=4)
        for i in range(6):
            ev.append("join", f"rank{i}")
        ev.append("leave", "rank0")
        snap = ev._snap()
        assert len(snap["log"]) == 4            # bounded
        assert snap["log"][-1][0] == 7          # monotonic seq survives trim
        counts = {tuple(sorted(l.items())): v for l, v in snap["counts"]}
        assert counts[(("kind", "join"),)] == 6.0

    def test_disabled_registry_is_noop(self):
        reg = Registry(enabled=False)
        c = reg.counter("hvd_tpu_test_e_total", help="h")
        assert c is _NOOP
        c.inc(5)                                 # lock-free no-op
        assert c.total() == 0.0
        snap = reg.snapshot()
        assert snap["enabled"] is False and snap["counters"] == {}


# ---------------------------------------------------------------------------
# Prometheus rendering
# ---------------------------------------------------------------------------

class TestPrometheusRender:
    def _sample_registry(self):
        reg = Registry()
        c = reg.counter("hvd_tpu_wire_bytes_total")
        c.inc(1024, kind="allreduce", dtype="float32")
        c.inc(64, kind="allgather", dtype="int32")
        reg.gauge("hvd_tpu_fusion_bucket_fill_pct").set(42.5)
        h = reg.histogram("hvd_tpu_op_latency_seconds", min_exp=-3,
                          max_exp=3)
        h.observe(0.3, kind="allreduce")
        reg.event_log("hvd_tpu_elastic_events").append("rank_join", "h:0")
        return reg

    def test_render_single(self):
        text = render_prometheus(self._sample_registry().snapshot())
        samples, type_lines = _parse_prom(text)
        by = {}
        for name, labels, v in samples:
            by.setdefault(name, []).append((labels, v))
        assert ({"kind": "allreduce", "dtype": "float32"}, 1024.0) \
            in by["hvd_tpu_wire_bytes_total"]
        assert by["hvd_tpu_fusion_bucket_fill_pct"] == [({}, 42.5)]
        assert any(l.get("le") == "+Inf" and v == 1.0
                   for l, v in by["hvd_tpu_op_latency_seconds_bucket"])
        assert by["hvd_tpu_op_latency_seconds_count"] == \
            [({"kind": "allreduce"}, 1.0)]
        assert by["hvd_tpu_elastic_events_total"] == \
            [({"kind": "rank_join"}, 1.0)]
        kinds = dict(type_lines)
        assert kinds["hvd_tpu_op_latency_seconds"] == "histogram"
        assert kinds["hvd_tpu_wire_bytes_total"] == "counter"

    def test_render_cluster_per_rank_labels(self):
        s0 = self._sample_registry().snapshot()
        reg1 = self._sample_registry()
        reg1.counter("hvd_tpu_wire_bytes_total").inc(
            512, kind="allreduce", dtype="float32")
        s1 = reg1.snapshot()
        text = render_prometheus_cluster({"0": s0, "1": s1})
        samples, type_lines = _parse_prom(text)
        # exactly one TYPE line per family even with two ranks
        names = [n for n, _ in type_lines]
        assert len(names) == len(set(names))
        wire = {l["rank"]: v for n, l, v in samples
                if n == "hvd_tpu_wire_bytes_total"
                and l.get("kind") == "allreduce"}
        assert wire == {"0": 1024.0, "1": 1536.0}

    def test_label_escaping(self):
        reg = Registry()
        reg.counter("hvd_tpu_test_esc_total", help="h").inc(
            1, reason='divergence "op #" \\ mid\nstep')
        text = render_prometheus(reg.snapshot())
        samples, _ = _parse_prom(text)
        [(name, labels, v)] = samples
        assert labels["reason"].startswith("divergence")


# ---------------------------------------------------------------------------
# KVStoreServer GET /metrics round-trip
# ---------------------------------------------------------------------------

import contextlib


@contextlib.contextmanager
def _isolated_registry():
    """Swap the process-global registry for a fresh empty one.

    The scrape endpoint merges the *server process's own* registry into
    the response under rank="driver" — by design (elastic telemetry on
    the launcher). In-process tests share one interpreter, so whatever
    counters earlier test files left in the global registry (engine wire
    bytes from test_stall/test_trace/test_chaos runs) would leak into
    these exact-value assertions. This was a real ORDER DEPENDENCE:
    TestScrapeEndpoint failed whenever registry-touching suites ran
    first (reproduced at PR 7 HEAD with `pytest tests/test_stall.py
    tests/test_trace.py tests/test_metrics.py::TestScrapeEndpoint`)."""
    with hmetrics._registry_lock:
        saved = hmetrics._registry
        hmetrics._registry = Registry()
    try:
        yield
    finally:
        with hmetrics._registry_lock:
            hmetrics._registry = saved


class TestScrapeEndpoint:
    @pytest.fixture(autouse=True)
    def _fresh_registry(self):
        with _isolated_registry():
            yield

    def test_scrape_isolated_from_polluted_process_registry(self):
        """Regression for the order dependence itself, in the
        non-alphabetical order: pollute the process registry the way an
        earlier engine/stall/trace suite does, THEN run the round-trip
        under the isolation this class now applies — the driver merge
        must not leak the polluted series into the assertions."""
        polluted = hmetrics.registry()   # the real global (fixture-swapped
        # to a fresh one, so this test's pollution is itself contained)
        polluted.counter("hvd_tpu_wire_bytes_total").inc(
            320.0, kind="allreduce", dtype="float32")
        polluted.counter("hvd_tpu_dispatches_total").inc(12)
        with _isolated_registry():
            self.test_kvstore_metrics_roundtrip()
            self.test_metrics_scrape_empty_store()

    def test_kvstore_metrics_roundtrip(self):
        from horovod_tpu.runner.http_server import KVStoreServer
        server = KVStoreServer(("127.0.0.1", 0))
        port = server.start()
        try:
            for rank in (0, 1):
                reg = Registry()
                reg.counter("hvd_tpu_wire_bytes_total").inc(
                    100 * (rank + 1), kind="allreduce", dtype="float32")
                reg.counter("hvd_tpu_dispatches_total").inc(7 + rank)
                hmetrics.publish_snapshot(("127.0.0.1", port), rank,
                                          reg.snapshot())
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=10) as resp:
                ctype = resp.headers.get("Content-Type", "")
                text = resp.read().decode()
            assert "text/plain" in ctype and "0.0.4" in ctype
            samples, type_lines = _parse_prom(text)
            names = [n for n, _ in type_lines]
            assert len(names) == len(set(names))
            wire = {l["rank"]: v for n, l, v in samples
                    if n == "hvd_tpu_wire_bytes_total"}
            assert wire == {"0": 100.0, "1": 200.0}
            disp = {l["rank"]: v for n, l, v in samples
                    if n == "hvd_tpu_dispatches_total"}
            assert disp == {"0": 7.0, "1": 8.0}
        finally:
            server.stop()

    def test_metrics_scrape_empty_store(self):
        from horovod_tpu.runner.http_server import KVStoreServer
        server = KVStoreServer(("127.0.0.1", 0))
        port = server.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=10) as resp:
                text = resp.read().decode()
            samples, _ = _parse_prom(text)     # parseable, just empty
            # ISSUE 18: the server accounts its own KV traffic, so the
            # driver merge may surface hvd_tpu_kv_request{s,_bytes}_total
            # (including this very scrape) — no OTHER telemetry allowed
            # from an empty store.
            extras = [s for s in samples
                      if not s[0].startswith("hvd_tpu_kv_request")]
            assert extras == []
        finally:
            server.stop()

    def test_rendezvous_server_inherits_metrics_route(self):
        from horovod_tpu.runner.http_server import RendezvousServer
        server = RendezvousServer(("127.0.0.1", 0))
        port = server.start()
        try:
            server.init([])
            reg = Registry()
            reg.counter("hvd_tpu_dispatches_total").inc(3)
            hmetrics.publish_snapshot(("127.0.0.1", port), 0, reg.snapshot())
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=10) as resp:
                samples, _ = _parse_prom(resp.read().decode())
            assert ("hvd_tpu_dispatches_total", {"rank": "0"}, 3.0) \
                in samples
        finally:
            server.stop()


# ---------------------------------------------------------------------------
# tools/check_metric_names.py (CI lint)
# ---------------------------------------------------------------------------

def _load_checker():
    spec = importlib.util.spec_from_file_location(
        "check_metric_names",
        os.path.join(REPO, "tools", "check_metric_names.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestMetricNameLint:
    # NOTE (ISSUE 7): the clean-tree wiring (declared specs lint-clean +
    # CLI exit 0) moved to the unified parametrized suite in
    # tests/test_check.py (tools/check.py runs every lint); only the
    # error-path unit test stays here next to the registry it exercises.

    def test_bad_specs_flagged(self):
        checker = _load_checker()
        errs = checker.validate_specs({
            "Bad-Name": ("counter", "h"),
            "hvd_tpu_no_help_total": ("counter", ""),
            "hvd_tpu_wrong_type": ("meter", "h"),
            "hvd_tpu_counter_without_suffix": ("counter", "h"),
        })
        joined = "\n".join(errs)
        assert "Bad-Name: does not match" in joined
        assert "hvd_tpu_no_help_total: missing help" in joined
        assert "unknown metric type 'meter'" in joined
        assert "hvd_tpu_counter_without_suffix: counters must end" in joined


# ---------------------------------------------------------------------------
# live engine instrumentation (size-1 in-process world)
# ---------------------------------------------------------------------------

class TestLiveInstrumentation:
    def test_engine_populates_registry(self):
        import horovod_tpu as hvd
        hvd.init()
        base = hvd.metrics_snapshot()
        assert base["enabled"] is True
        hvd.allreduce(np.ones(16, np.float32), name="met.ar", op=hvd.Sum)
        hvd.grouped_allreduce(
            [np.ones(4, np.float32), np.ones((2, 3), np.float32)],
            name="met.g", op=hvd.Sum)
        snap = hvd.metrics_snapshot()
        # wire bytes: 64 (allreduce) + 16 + 24 (grouped)
        assert _tot(snap, "hvd_tpu_wire_bytes_total") \
            - _tot(base, "hvd_tpu_wire_bytes_total") == 104.0
        assert _tot(snap, "hvd_tpu_dispatches_total") \
            > _tot(base, "hvd_tpu_dispatches_total")
        kinds = {tuple(sorted(l.items()))
                 for l, _ in snap["counters"]["hvd_tpu_wire_bytes_total"]
                 ["values"]}
        # every wire series carries the fabric-link label (ISSUE 10);
        # a size-1 world moves everything over link="flat"
        assert (("dtype", "float32"), ("kind", "allreduce"),
                ("link", "flat")) in kinds
        # the sync allreduce retires through synchronize -> latency observed
        lat = snap["histograms"]["hvd_tpu_op_latency_seconds"]["values"]
        assert any(l.get("kind") == "allreduce" and ent["count"] >= 1
                   for l, ent in lat)
        # bucket accounting moved with the grouped call
        assert _tot(snap, "hvd_tpu_fusion_buckets_total") \
            - _tot(base, "hvd_tpu_fusion_buckets_total") >= 1

    def test_jsonl_emitter(self, tmp_path, monkeypatch):
        import horovod_tpu as hvd
        hvd.shutdown()
        path = str(tmp_path / "metrics.jsonl")
        monkeypatch.setenv("HOROVOD_TPU_METRICS_FILE", path)
        monkeypatch.setenv("HOROVOD_TPU_METRICS_INTERVAL", "3600")
        hvd.init()
        hvd.allreduce(np.ones(4, np.float32), name="emit.ar", op=hvd.Sum)
        hvd.shutdown()                   # final flush writes one record
        with open(path) as f:
            lines = [json.loads(l) for l in f if l.strip()]
        assert lines, "emitter wrote nothing"
        rec = lines[-1]
        assert rec["rank"] == 0
        assert "hvd_tpu_wire_bytes_total" in rec["metrics"]["counters"]

    def test_metrics_disabled_no_dispatch_bookkeeping(self, monkeypatch):
        import horovod_tpu as hvd
        from horovod_tpu import metrics
        hvd.shutdown()
        monkeypatch.setenv("HOROVOD_TPU_METRICS", "0")
        metrics._reset_registry_for_tests()
        try:
            hvd.init()
            eng = hvd._engine()
            assert eng._m_enabled is False
            assert eng._m_dispatches is _NOOP
            hvd.allreduce(np.ones(4, np.float32), name="dis.ar", op=hvd.Sum)
            snap = hvd.metrics_snapshot()
            assert snap["enabled"] is False and snap["counters"] == {}
        finally:
            hvd.shutdown()
            monkeypatch.setenv("HOROVOD_TPU_METRICS", "1")
            metrics._reset_registry_for_tests()


# ---------------------------------------------------------------------------
# np=2: publish -> aggregate -> scrape, numbers reconcile with snapshots
# ---------------------------------------------------------------------------

def _worker_metrics_scrape():
    import os
    import urllib.request
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import horovod_tpu as hvd
    from horovod_tpu import metrics as hmetrics

    rank = hvd.rank()
    # six identical replay-bracketed steps: arm at streak 3 (default
    # warmup), replay the tail -> armed/replayed counters move
    for i in range(6):
        with hvd.step():
            hs = hvd.grouped_allreduce_async(
                [np.ones(8, np.float32), np.ones((2, 2), np.float32)],
                name=f"mg{i}", op=hvd.Sum)
        for h in hs:
            h.synchronize()
    # one divergent step (plain allreduce doesn't match the armed grouped
    # stream) -> a replay fallback
    with hvd.step():
        hvd.allreduce(np.ones(4, np.float32), name="mdiv", op=hvd.Sum)
    hvd.barrier()
    snap = hvd.metrics_snapshot()
    addr = os.environ["HOROVOD_GLOO_RENDEZVOUS_ADDR"]
    port = int(os.environ["HOROVOD_GLOO_RENDEZVOUS_PORT"])
    hmetrics.publish_snapshot((addr, port), rank, snap)
    # wait for every rank's publish by polling the KV — NOT a barrier: a
    # collective here would advance the counters after the snapshot, and
    # the emitter's shutdown final-flush republish would then diverge from
    # the snapshot this worker returns (scrape reconciliation would race)
    from horovod_tpu.runner.http_client import read_data_from_kvstore
    for r in range(hvd.size()):
        read_data_from_kvstore(addr, port, "metrics", str(r), timeout=30)
    text, ctype = None, None
    if rank == 0:
        with urllib.request.urlopen(f"http://{addr}:{port}/metrics",
                                    timeout=15) as resp:
            ctype = resp.headers.get("Content-Type", "")
            text = resp.read().decode()

    def tot(name):
        ent = snap["counters"].get(name, {"values": []})
        return sum(v for _, v in ent["values"])

    return {"rank": rank,
            "wire": tot("hvd_tpu_wire_bytes_total"),
            "disp": tot("hvd_tpu_dispatches_total"),
            "armed": tot("hvd_tpu_replay_armed_total"),
            "replayed": tot("hvd_tpu_replay_replayed_steps_total"),
            "fallbacks": tot("hvd_tpu_replay_fallbacks_total"),
            "text": text, "ctype": ctype}


@pytest.mark.integration
@pytest.mark.skipif(os.environ.get("HVD_TPU_SKIP_MULTIPROC") == "1",
                    reason="multi-process tier disabled")
def test_two_rank_scrape_reconciles_with_snapshots():
    """ISSUE 3 acceptance: a two-rank run scraped via GET /metrics on the
    rendezvous server returns Prometheus-parseable text whose per-rank
    wire-byte/dispatch/replay counters equal each worker's own
    hvd.metrics_snapshot()."""
    from horovod_tpu.runner import run
    env = {
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "HOROVOD_STALL_CHECK_DISABLE": "1",
        # periodic emitter must not overwrite the deterministic publish
        "HOROVOD_TPU_METRICS_INTERVAL": "3600",
    }
    results = run(_worker_metrics_scrape, np=2, env=env)
    r0 = next(r for r in results if r["rank"] == 0)
    assert r0["text"], "rank 0 scraped nothing"
    assert "text/plain" in r0["ctype"]
    samples, type_lines = _parse_prom(r0["text"])
    names = [n for n, _ in type_lines]
    assert len(names) == len(set(names)), "duplicate TYPE lines"
    for r in results:
        rk = str(r["rank"])

        def scraped(name):
            return sum(v for n, l, v in samples
                       if n == name and l.get("rank") == rk)

        assert r["wire"] > 0
        assert scraped("hvd_tpu_wire_bytes_total") == \
            pytest.approx(r["wire"]), rk
        assert scraped("hvd_tpu_dispatches_total") == \
            pytest.approx(r["disp"]), rk
        assert r["armed"] >= 1 and r["replayed"] >= 1, r
        assert scraped("hvd_tpu_replay_armed_total") == \
            pytest.approx(r["armed"]), rk
        assert r["fallbacks"] >= 1, r
        assert scraped("hvd_tpu_replay_fallbacks_total") == \
            pytest.approx(r["fallbacks"]), rk
