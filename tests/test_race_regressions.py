"""Regression tests for the true lock-discipline violations the ISSUE 7
lockcheck surfaced in existing code, each exercising the racy
interleaving the fix closed:

- engine ZeRO-1 prefetch registry: dispatch-path writes raced the
  invalidation sweep's dict iteration (pre-fix: RuntimeError "dictionary
  changed size during iteration");
- elastic driver results table: process-monitor threads wrote
  ``_results`` off-lock while ``get_results`` copied it (pre-fix: same
  RuntimeError class);
- stall inspector ``_warned``: the watch thread warned and THEN added
  the name off the membership lock — a ``record_done`` landing between
  the two leaked a stale ``_warned`` entry that suppressed any future
  stall warning for that name (reproduced deterministically via a log
  handler that retires the op from inside the warning itself);
- trace recorder ``live_corr``: read ``_live`` off-lock while the cycle
  thread's ``record_done`` popped it (GIL-atomic in CPython today, so
  this one is a discipline check: the locked read must return either
  the live corr or None under churn, never crash or tear).

The first three fail against the pre-fix code; the interleaving knobs
(``sys.setswitchinterval`` and the handler injection) make the schedules
that used to need unlucky timing near-certain.
"""

import logging
import sys
import threading
import time

import pytest

import horovod_tpu as hvd
from horovod_tpu.stall_inspector import StallInspector
from horovod_tpu.trace import TraceRecorder

# plain tier-1 runtime tests — deliberately NOT `-m lint`: that marker is
# the static-analysis suite, and these initialize a live engine and churn
# real threads
N_ROUNDS = 400


@pytest.fixture()
def engine():
    hvd.init()
    yield hvd._engine()


@pytest.fixture()
def fast_switches():
    """Force thread switches every few bytecodes so a cross-thread dict
    mutation lands inside any unguarded iteration with near-certainty."""
    prev = sys.getswitchinterval()
    sys.setswitchinterval(1e-6)
    yield
    sys.setswitchinterval(prev)


class TestPrefetchRegistryRace:
    def test_concurrent_note_and_invalidate(self, engine, fast_switches):
        """Writers register fresh legs (growing the dict) while a sweeper
        iterates it for GC and clears it for invalidation: pre-fix the
        sweep crashed with 'dictionary changed size during iteration';
        post-fix no exception and every leg is accounted exactly once."""
        eng = engine
        eng.invalidate_prefetch("test isolation")
        inval0 = eng._m_prefetch_inval.value()
        # several independent rounds: one racy schedule can get lucky,
        # three back to back (under 1 microsecond switch intervals) cannot
        for _ in range(3):
            errors = self._one_round(eng)
            assert not errors, errors
        # drain: a final invalidate accounts every still-held leg
        eng.invalidate_prefetch("final drain")
        assert not eng._zero1_prefetch
        noted = eng._m_prefetch.value()
        dropped = eng._m_prefetch_inval.value() - inval0
        assert dropped <= noted

    @staticmethod
    def _one_round(eng):
        stop = threading.Event()
        errors = []

        def noter():
            try:
                i = 0
                while not stop.is_set():
                    # fresh keys: the registry keeps growing, so the
                    # sweeper's iteration always races live inserts
                    eng._note_prefetch(("bucket", i))
                    i += 1
            except Exception as e:  # pragma: no cover - the regression
                errors.append(e)

        def sweeper():
            try:
                # sweep only once the registry is busy: a sweep over a
                # near-empty dict finishes in too few bytecodes to overlap
                # an insert, and the pre-fix crash needs the overlap
                deadline = time.monotonic() + 10
                while len(eng._zero1_prefetch) < 200 and \
                        time.monotonic() < deadline:
                    pass
                for j in range(N_ROUNDS * 2):
                    if j % 10 == 9:
                        eng.invalidate_prefetch(f"round {j}")
                    else:
                        eng._prefetch_gc()
            except Exception as e:  # pragma: no cover - the regression
                errors.append(e)

        threads = [threading.Thread(target=noter) for _ in range(2)]
        sw = threading.Thread(target=sweeper)
        for t in threads:
            t.start()
        sw.start()
        sw.join(timeout=60)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        return errors

    def test_gc_drops_only_stale_world_versions(self, engine):
        eng = engine
        eng.invalidate_prefetch("test isolation")
        eng._note_prefetch(("keep",))
        with eng._lock:
            eng._zero1_prefetch[("stale",)] = {
                "world_version": eng.world_version - 1}
        eng._prefetch_gc()
        assert ("keep",) in eng._zero1_prefetch
        assert ("stale",) not in eng._zero1_prefetch
        eng.invalidate_prefetch("test isolation")


class TestDriverResultsRace:
    def _driver(self):
        from horovod_tpu.elastic.driver import ElasticDriver
        from horovod_tpu.elastic.discovery import HostDiscovery

        class _FixedDiscovery(HostDiscovery):
            def find_available_hosts_and_slots(self):
                return {"localhost": 4}

        class _NullRendezvous:
            def init(self, assignments):
                pass

        return ElasticDriver(_NullRendezvous(), _FixedDiscovery(),
                             min_np=1, max_np=8)

    def test_concurrent_exits_vs_result_reads(self, fast_switches):
        """Process monitors record exits from their own threads while the
        run loop snapshots get_results: pre-fix the off-lock dict copy
        raced the growing table ('dictionary changed size during
        iteration'); post-fix every exit lands and nothing raises."""
        driver = self._driver()
        errors = []
        stop = threading.Event()
        n_threads, per_thread = 4, 250

        def monitor(tid):
            try:
                for i in range(per_thread):
                    driver.record_worker_exit(f"host{tid}", i, 0,
                                              result=(tid, i))
            except Exception as e:  # pragma: no cover - the regression
                errors.append(e)

        def reader():
            try:
                while not stop.is_set():
                    driver.get_results()
            except Exception as e:  # pragma: no cover - the regression
                errors.append(e)

        threads = [threading.Thread(target=monitor, args=(t,))
                   for t in range(n_threads)]
        rd = threading.Thread(target=reader)
        rd.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        stop.set()
        rd.join(timeout=10)
        assert not errors, errors
        results = driver.get_results()
        assert len(results) == n_threads * per_thread
        for tid in range(n_threads):
            for i in range(per_thread):
                assert results[f"host{tid}:{i}"] == ((tid, i), 0)


class TestStallWarnedRace:
    def test_completion_during_warning_leaves_no_stale_entry(self):
        """Deterministic reproduction of the _warned leak: a log handler
        retires the op from INSIDE the stall warning — the exact moment a
        cycle-thread completion used to land. Pre-fix the watch thread
        then added the already-completed name to _warned, permanently
        suppressing any future stall warning for it; post-fix the name is
        added under the lock only while still outstanding, and the
        completion's discard erases it."""
        insp = StallInspector(warning_seconds=0.0, check_interval=0.01,
                              kv=None)
        fired = threading.Event()

        class _CompleteOnWarn(logging.Handler):
            def emit(self, record):
                msg = record.getMessage()
                if "have not completed" in msg and "race.op" in msg:
                    insp.record_done("race.op")
                    fired.set()

        handler = _CompleteOnWarn()
        logging.getLogger("horovod_tpu").addHandler(handler)
        try:
            insp.record_enqueue("race.op")
            assert fired.wait(timeout=10), "stall warning never fired"
            time.sleep(0.05)  # a couple more watch ticks
            with insp._lock:
                outstanding = dict(insp._outstanding)
                warned = set(insp._warned)
            assert outstanding == {}
            assert "race.op" not in warned, (
                "stale _warned entry leaked: a stall of a later op named "
                "'race.op' would never be warned about")
        finally:
            logging.getLogger("horovod_tpu").removeHandler(handler)
            insp.stop()


class TestRegistrationPutOrdering:
    def test_superseded_init_put_is_skipped(self, monkeypatch):
        """A delayed init() PUT must never land after a reregister() and
        re-advertise a stale rank key: each registration bumps an epoch,
        and a PUT whose epoch was superseded skips instead of writing."""
        from horovod_tpu.elastic import worker as worker_mod
        mgr = worker_mod.WorkerNotificationManager()
        puts = []
        monkeypatch.setattr(
            worker_mod, "put_data_into_kvstore",
            lambda addr, port, scope, key, value, **kw:
                puts.append((key, value)))
        with mgr._lock:
            mgr._reg_epoch += 1
            stale_epoch = mgr._reg_epoch      # init captured this...
            mgr._reg_epoch += 1               # ...then a reregister ran
            fresh_epoch = mgr._reg_epoch
        assert mgr._registration_put(stale_epoch, "h", 1, 3,
                                     "old:1") is False
        assert puts == []                     # stale write never issued
        assert mgr._registration_put(fresh_epoch, "h", 1, 4,
                                     "new:1") is True
        assert puts == [("4", b"new:1")]


class TestLiveCorrRace:
    def test_live_corr_under_concurrent_retirement(self):
        """The timeline hook reads live_corr while another thread (the
        cycle loop in production) retires the same names: the locked read
        returns the live corr or None, never a crash or a torn value."""
        rec = TraceRecorder(rank=0, capacity=128)
        errors = []
        stop = threading.Event()

        def churn():
            try:
                for i in range(N_ROUNDS * 4):
                    rec.record_enqueue(f"t{i % 8}", "allreduce", 64, 0)
                    rec.record_done(f"t{i % 8}")
            except Exception as e:  # pragma: no cover - the regression
                errors.append(e)
            finally:
                stop.set()

        def reader():
            try:
                while not stop.is_set():
                    for i in range(8):
                        corr = rec.live_corr(f"t{i}")
                        assert corr is None or corr.startswith(f"t{i}#")
            except Exception as e:  # pragma: no cover - the regression
                errors.append(e)

        t1, t2 = threading.Thread(target=churn), \
            threading.Thread(target=reader)
        t1.start()
        t2.start()
        t1.join(timeout=30)
        t2.join(timeout=30)
        assert not errors, errors
        # everything retired: no live correlation ids remain
        assert all(rec.live_corr(f"t{i}") is None for i in range(8))
