"""Distributed optimizer: SPMD data-parallel training over 8 devices must
reproduce single-device full-batch training (the correctness contract of the
reference's DistributedOptimizer), plus accumulation and compression paths."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import optimizer as hopt
from horovod_tpu.models.mlp import init_mlp, mlp_loss
from horovod_tpu.ops.compression import Compression


def _batch(n=64, din=16, nclass=4, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, din).astype(np.float32)
    y = rng.randint(0, nclass, size=(n,)).astype(np.int32)
    return x, y


def _params():
    return init_mlp(jax.random.PRNGKey(0), sizes=(16, 32, 4))


def test_spmd_dp_matches_single_device():
    mesh = Mesh(np.array(jax.devices()), ("world",))
    params = _params()
    opt_inner = optax.sgd(0.05)
    x, y = _batch()

    # single-device reference
    ref_p, ref_s = params, opt_inner.init(params)
    for _ in range(3):
        g = jax.grad(mlp_loss)(ref_p, (x, y))
        u, ref_s = opt_inner.update(g, ref_s, ref_p)
        ref_p = optax.apply_updates(ref_p, u)

    # SPMD: batch sharded over 8 devices, distributed optax wrapper inside
    # a shard_mapped step. Per-shard grad is the *local mean*; op=Average
    # then averages across shards == global mean.
    dist = hopt.distributed(opt_inner, axis_name="world", op=hvd.Average)

    def local_step(params, opt_state, xb, yb):
        g = jax.grad(mlp_loss)(params, (xb, yb))
        u, opt_state = dist.update(g, opt_state, params)
        return optax.apply_updates(params, u), opt_state

    step = jax.jit(jax.shard_map(
        local_step, mesh=mesh,
        in_specs=(P(), P(), P("world"), P("world")),
        out_specs=(P(), P())))

    sh = NamedSharding(mesh, P("world"))
    xb, yb = jax.device_put(x, sh), jax.device_put(y, sh)
    p = jax.device_put(params, NamedSharding(mesh, P()))
    s = dist.init(p)
    for _ in range(3):
        p, s = step(p, s, xb, yb)

    for a, b in zip(jax.tree_util.tree_leaves(p), jax.tree_util.tree_leaves(ref_p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4,
                                   atol=1e-5)


def test_backward_passes_per_step_accumulation():
    mesh = Mesh(np.array(jax.devices()), ("world",))
    params = _params()
    inner = optax.sgd(0.1)
    dist = hopt.distributed(inner, axis_name="world", op=hvd.Average,
                            backward_passes_per_step=2)

    def local_step(params, state, xb, yb):
        g = jax.grad(mlp_loss)(params, (xb, yb))
        u, state = dist.update(g, state, params)
        return optax.apply_updates(params, u), state

    step = jax.jit(jax.shard_map(
        local_step, mesh=mesh,
        in_specs=(P(), P(), P("world"), P("world")),
        out_specs=(P(), P())))

    sh = NamedSharding(mesh, P("world"))
    x, y = _batch(seed=3)
    xb, yb = jax.device_put(x, sh), jax.device_put(y, sh)
    p0 = jax.device_put(params, NamedSharding(mesh, P()))
    s = dist.init(p0)
    # pass 1: accumulate only — params unchanged
    p1, s = step(p0, s, xb, yb)
    for a, b in zip(jax.tree_util.tree_leaves(p0), jax.tree_util.tree_leaves(p1)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # pass 2: reduction + update — params change
    p2, s = step(p1, s, xb, yb)
    changed = any(not np.allclose(np.asarray(a), np.asarray(b))
                  for a, b in zip(jax.tree_util.tree_leaves(p1),
                                  jax.tree_util.tree_leaves(p2)))
    assert changed


def test_eager_distributed_optimizer_size1():
    hvd.init()
    params = _params()
    opt = hvd.optimizer.DistributedOptimizer(optax.sgd(0.05))
    state = opt.init(params)
    x, y = _batch(seed=5)
    losses = []
    for _ in range(5):
        loss, grads = jax.value_and_grad(mlp_loss)(params, (x, y))
        params, state = opt.update_and_apply(grads, state, params)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_compression_roundtrip_in_reduction():
    # Varying (per-shard) grads: explicit collective path with bf16 wire format.
    mesh = Mesh(np.array(jax.devices()), ("world",))
    per_shard = np.arange(8, dtype=np.float32)[:, None] * np.ones((1, 4))

    def reduce_local(g):
        out = hopt.allreduce_gradients({"w": g[0]}, "world", hvd.Average,
                                       compression=Compression.bf16)
        return out["w"][None]

    fn = jax.jit(jax.shard_map(reduce_local, mesh=mesh, in_specs=(P("world"),),
                               out_specs=P("world")))
    out = np.asarray(fn(jax.device_put(
        jnp.asarray(per_shard), NamedSharding(mesh, P("world")))))
    assert out.dtype == np.float32
    np.testing.assert_allclose(out, 3.5, rtol=1e-2)  # mean(0..7)


def test_presummed_average_divides_only():
    # Unvarying leaf (the shard_map-transpose pre-summed case): Average must
    # divide by the axis size and not psum again.
    mesh = Mesh(np.array(jax.devices()), ("world",))

    def body(w, x):
        g = jax.grad(lambda w: jnp.mean(x) * jnp.sum(w * w))(w)  # pre-summed
        out = hopt.allreduce_gradients({"w": g}, "world", hvd.Average)
        return out["w"]

    fn = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=(P(), P("world")),
                               out_specs=P()))
    w = jnp.ones((4,), jnp.float32)
    x = np.arange(64, dtype=np.float32).reshape(8, 8)
    out = np.asarray(fn(w, jax.device_put(
        jnp.asarray(x), NamedSharding(mesh, P("world")))))
    # d/dw mean_over_shards( mean(x_i) * sum(w^2) ) = 2 * mean(x) * w
    np.testing.assert_allclose(out, 2 * x.mean() * np.ones(4), rtol=1e-5)
