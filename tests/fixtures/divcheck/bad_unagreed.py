"""divcheck fixture: rank-local values flowing into collective decisions."""
import os
import time

from horovod_tpu.ops.collectives import bucket_by_size, choose_algorithm


def env_into_selection(kind, nbytes, topo):
    return choose_algorithm(kind, nbytes, topo,  # VIOLATION: env into selection
                            force=os.environ.get("MY_ALGO"))


def tainted_threshold(tensors):
    threshold = int(os.environ.get("MY_THRESHOLD", "1024"))  # tainted here
    return bucket_by_size(tensors, threshold)  # VIOLATION: tainted name into sink


def time_into_layout(tensors):
    return bucket_by_size(tensors, int(time.monotonic()))  # VIOLATION: time into sink


def agreed_is_fine(tensors):
    threshold = int(os.environ.get("MY_THRESHOLD", "1024"))  # divcheck: agreed[launcher exports one env to every rank before spawn]
    return bucket_by_size(tensors, threshold)
