"""divcheck fixture: suppression/annotation hygiene."""
import horovod_tpu as hvd


def reasonless(grads, rank):
    if rank == 0:
        return hvd.allreduce(grads)  # divcheck: ignore
    return grads


def stale():
    # divcheck: ignore[old excuse for code that changed]
    return 1


def agreed_without_how(grads, rank):
    if rank == 0:  # divcheck: agreed[]
        return hvd.allreduce(grads)
    return grads


def stale_agreed(grads):
    if len(grads) > 2:  # divcheck: agreed[nothing here is rank-local]
        return hvd.allreduce(grads)
    return grads
