"""divcheck fixture: impure reads on the step path (capture hazards)."""
import os

from horovod_tpu.ops.collectives import build_grouped_allreduce


class MiniEngine:
    def __init__(self):
        # init-phase exemption: resolving knobs at construction is the
        # sanctioned pattern — this read must NOT be a finding
        self.threshold = int(os.environ.get("MY_THRESHOLD", "1024"))

    def allreduce(self, tensors):
        live = os.environ.get("MY_LIVE_KNOB")  # VIOLATION: env read on step path
        self._stage(tensors)
        return build_grouped_allreduce(tensors, live)

    def _stage(self, tensors):
        for f in os.listdir("/tmp"):  # VIOLATION: host I/O on step path
            tensors.append(f)


def off_path_read():
    # not reachable from any step-path root: reading env here is fine
    return os.environ.get("MY_OFFLINE_KNOB")
