"""divcheck fixture: collectives submitted in nondeterministic order."""
import os

import horovod_tpu as hvd


def over_set(named_grads):
    handles = {}
    for name in set(named_grads):
        handles[name] = hvd.allreduce(named_grads[name], name=name)  # VIOLATION: set iteration
    return handles


def over_listdir(eng, directory):
    out = []
    for fn in os.listdir(directory):
        out.append(eng.broadcast_object(fn))  # VIOLATION: listdir iteration
    return out


class Tracker:
    def __init__(self):
        self._dirty = set()

    def flush(self, eng):
        for name in self._dirty:
            eng.allreduce(name)  # VIOLATION: set attribute iteration
        self._dirty = set()


def sorted_is_fine(eng, directory):
    out = []
    for fn in sorted(os.listdir(directory)):
        out.append(eng.broadcast_object(fn))
    return out
