"""divcheck cross-file fixture: the rank gate lives here — only the
cross-file call graph connects it to the collective in helper.py."""
from .helper import sync_gradients


def maybe_sync(grads, rank):
    if rank == 0:
        return sync_gradients(grads)  # VIOLATION: cross-file rank gate
    return grads
