"""divcheck cross-file fixture: the collective lives here."""
import horovod_tpu as hvd


def sync_gradients(grads):
    return [hvd.allreduce(g, name=f"g.{i}") for i, g in enumerate(grads)]
