"""divcheck fixture: rank-gated collectives — the classic SPMD deadlock."""
import os

import horovod_tpu as hvd


def direct_gate(grads):
    if hvd.rank() == 0:
        hvd.allreduce(grads, name="g")  # VIOLATION: if-gated collective
    return grads


def guard_return_gate(eng, grads):
    if eng.backend.local_rank() != 0:
        return grads
    return eng.grouped_allreduce(grads)  # VIOLATION: guard-return gated


class Elastic:
    def __init__(self):
        self.world_version = 0

    def maybe_sync(self, eng, observed):
        if observed != self.world_version:
            eng.barrier()  # VIOLATION: world-version gated
        return observed


def else_branch_gate(eng, x, rank):
    if rank == 0:
        prep = x * 2
    else:
        prep = eng.broadcast(x, 0)  # VIOLATION: else-arm gated
    return prep
