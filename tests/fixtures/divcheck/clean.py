"""divcheck fixture: lockstep-correct code — zero findings expected."""
import os

import horovod_tpu as hvd


def data_prep_gate(eng, x, root_rank):
    # rank-gated DATA PREP with the collective outside the branch: the
    # canonical broadcast_object shape, and not a finding
    if eng.backend.rank() == root_rank:
        payload = x * 2
    else:
        payload = x * 0
    return eng.broadcast(payload, root_rank)


def size_gate_is_agreed(eng, grads):
    # world size is collectively identical — gating on it is lockstep
    if eng.backend.size() == 1:
        return grads
    return eng.grouped_allreduce(grads)


def ordered_iteration(eng, directory, named):
    out = [eng.broadcast_object(f) for f in sorted(os.listdir(directory))]
    for name in named:  # a list: submission order is the program order
        out.append(hvd.allreduce(named[name], name=name))
    return out


class Warmup:
    def __init__(self):
        # init-phase knob resolution: the sanctioned pattern
        self.threshold = int(os.environ.get("MY_THRESHOLD", "1024"))
        self.world_version = 0

    def agreed_condition(self, eng, observed):
        # divcheck: agreed[bumps are rendezvous-stamped before any rank re-enters a step]
        if observed != self.world_version:
            eng.barrier()
            self.world_version = observed
        return observed
