"""errflow fixture: raw transport calls with neither a deadline nor a
``retrying()`` wrapper."""
import socket
import urllib.request

from horovod_tpu.common.retry import retrying


def no_deadline(url):
    return urllib.request.urlopen(url)  # VIOLATION: deadline-less urlopen


def sock_no_deadline(addr):
    conn = socket.create_connection(addr)  # VIOLATION: deadline-less connect
    try:
        return conn.recv(1)
    finally:
        conn.close()


def with_deadline(url):
    return urllib.request.urlopen(url, timeout=5)


def wrapped(url):
    def _attempt():
        return urllib.request.urlopen(url)  # retrying()-owned: not flagged
    return retrying(_attempt, attempts=3, deadline=10.0)
