"""errflow fixture: swallowed recovery-class errors on the recovery
path — and the sanctioned shapes that must NOT be flagged."""


class Handle:
    def synchronize(self):  # recovery root
        try:
            self._wait()
        except Exception:
            self.done = True  # VIOLATION: swallowed broad except

    def _wait(self):
        raise RuntimeError("boom")


def _dispatch(work, helper_on_path):  # recovery root
    try:
        work()
    except BaseException:
        work.failed = True  # VIOLATION: swallowed BaseException
    try:
        work()
    except HorovodInternalError:  # noqa: F821 — name-level fixture
        work.count = 1  # VIOLATION: swallowed recovery carrier
    helper_on_path()
    reraise_ok(work)
    return_ok(work)
    escalate_ok(work, work)
    later_raise_ok(work)
    probe_ok()
    tail_ok(work)
    loop_ok(work)


def helper_on_path():
    try:
        step()  # noqa: F821
    except Exception:
        state = "degraded"  # noqa: F841  VIOLATION: reachable helper swallows
    print(state)  # noqa: F821


def reraise_ok(work):
    try:
        work()
    except Exception:
        raise


def return_ok(work):
    try:
        work()
    except Exception:
        return None


def escalate_ok(work, engine):
    try:
        work()
    except Exception as e:
        engine.poison(e)


def later_raise_ok(work):
    last = None
    for _ in range(3):
        try:
            work()
            break
        except Exception as e:
            last = e
    if last is not None:
        raise last


def probe_ok():
    try:
        import does_not_exist_anywhere  # noqa: F401
    except Exception:
        pass


def tail_ok(work):
    ok = False
    try:
        work()
        ok = True
    except Exception:
        ok = False
    return ok


def loop_ok(work):
    while True:
        if work.expired:
            raise TimeoutError("deadline")
        try:
            return work()
        except Exception as e:
            work.last = e


def off_path_helper(work):
    """NOT reachable from any recovery root: a broad swallow here is
    outside this finding class (lifecycle/seam rules still apply)."""
    try:
        work()
    except Exception:
        pass
