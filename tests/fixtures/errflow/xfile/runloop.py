"""errflow cross-file fixture: the recovery root lives here; the
swallow it reaches lives in helper.py (the call graph is name-resolved
across every file of the run)."""


def run_fn(func, reset):
    def wrapper(state):
        fetch_state(state)  # noqa: F821 — resolved by name across files
        return func(state)
    return wrapper
