"""errflow cross-file fixture: reachable from runloop.py's ``run_fn``."""


def fetch_state(state):
    try:
        state.load()
    except Exception:
        state.cached = True  # VIOLATION: cross-file swallow


def unreached(state):
    try:
        state.load()
    except Exception:
        state.cached = True  # not reachable from the root: not flagged
