"""errflow fixture: every pattern here is sanctioned — zero findings.

Recovery-path handlers that re-raise/return/escalate, deadline-carrying
transport calls, context-managed resources, joined threads, observable
seams, and a drift-free failpoint registry.
"""
import logging
import socket
import threading
import urllib.request

from horovod_tpu.common.retry import retrying

logger = logging.getLogger(__name__)

FAULT_SPECS = {
    "clean.publish": "the one declared-and-placed failpoint",
}


def synchronize(handle):
    """A recovery root whose broad except re-raises: propagation OK."""
    try:
        return handle.wait()
    except Exception:
        handle.teardown()
        raise


def _dispatch(work, engine):
    """Escalation counts as propagation."""
    try:
        work()
    except Exception as e:
        engine.poison(e)


def fetch_with_deadline(url):
    return urllib.request.urlopen(url, timeout=5)


def fetch_retry_wrapped(url):
    def _attempt():
        return urllib.request.urlopen(url)
    return retrying(_attempt, attempts=3, deadline=10.0)


def probe(addr):
    with socket.create_connection(addr, timeout=2):
        return True


def read_config(path):
    with open(path) as f:
        return f.read()


def read_finally(path):
    f = open(path)
    try:
        return f.read()
    finally:
        f.close()


def open_for_caller(path):
    f = open(path)
    return f  # ownership transfer: the caller owns the close


def run_workers(jobs):
    threads = [threading.Thread(target=j) for j in jobs]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


class Publisher:
    def __init__(self, target):
        self._thread = threading.Thread(target=target, daemon=True)
        self._thread.start()

    def stop(self):
        self._thread.join(timeout=5)


def observable_publish(kv, payload, counter):
    """A declared seam whose degraded mode is counted: observable."""
    from horovod_tpu.faults import failpoint
    failpoint("clean.publish")
    try:
        kv.put(payload)
    except Exception as e:
        counter.inc()
        logger.warning("publish failed: %s", e)
