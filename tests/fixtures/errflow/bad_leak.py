"""errflow fixture: resources whose release is missing from the
exception edge (files/sockets) or from any shutdown path (threads)."""
import socket
import threading


def success_path_close(path, sink):
    f = open(path)  # VIOLATION: closed only on the success path
    sink.write(f.read())
    f.close()


def never_closed(path, sink):
    f = open(path)  # VIOLATION: never closed
    sink.write(f.read())


def socket_success_close(addr):
    s = socket.socket()  # VIOLATION: bind may raise before close
    s.bind(addr)
    port = s.getsockname()[1]
    s.close()
    return port


def local_thread_no_join(job):
    t = threading.Thread(target=job)  # VIOLATION: started, never joined
    t.start()


def fire_and_forget(job):
    threading.Thread(target=job, daemon=True).start()  # VIOLATION: untracked


class NoJoinWorker:
    def start(self, job):
        self._t = threading.Thread(target=job)  # VIOLATION: no method joins
        self._t.start()


class JoinedWorker:
    def start(self, job):
        self._t = threading.Thread(target=job)
        self._t.start()

    def stop(self):
        self._t.join(timeout=5)
