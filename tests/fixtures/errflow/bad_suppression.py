"""errflow fixture: suppression hygiene — reasonless and stale
suppressions are themselves findings; a reasoned one is enumerated."""


def synchronize(work):
    try:
        work()
    except Exception:  # errflow: ignore[]
        work.done = True  # BAD: suppression without a reason


def _dispatch(work):
    try:
        work()
    # errflow: ignore[fixture: deliberate best-effort swallow, reasoned]
    except Exception:
        work.done = True  # suppressed OK — enumerated in the report


# errflow: ignore[stale: the code this excused is gone]
def clean_helper(x):
    return x
