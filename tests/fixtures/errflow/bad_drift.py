"""errflow fixture: FAULT_SPECS vs failpoint() drift, both directions."""
from horovod_tpu.faults import failpoint

FAULT_SPECS = {
    "ok.placed": "a declared and placed failpoint",
    "dead.name": "declared but unplaced",  # VIOLATION: dead declaration
}


def f(name):
    failpoint("ok.placed")
    failpoint("un.declared")  # VIOLATION: undeclared name
    failpoint("test.reserved")  # VIOLATION: reserved prefix
    failpoint(name)  # VIOLATION: computed name
