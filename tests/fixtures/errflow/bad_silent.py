"""errflow fixture: except blocks on declared seams that are neither
observable nor propagating."""
import logging

from horovod_tpu.faults import failpoint

logger = logging.getLogger(__name__)


def silent_failpoint_seam(kv, payload):
    failpoint("pub.send")
    try:
        kv.put(payload)
    except Exception:
        payload.dropped = True  # VIOLATION: silent degraded mode


# errflow: seam[degraded KV write path declared without a failpoint]
def silent_tagged_seam(kv, payload):
    try:
        kv.put(payload)
    except Exception:
        pass  # VIOLATION: silent tagged seam


def warning_seam(kv, payload):
    failpoint("pub.warned")
    try:
        kv.put(payload)
    except Exception as e:
        logger.warning("publish failed: %s", e)  # observable: not flagged


def counted_seam(kv, payload, counter):
    failpoint("pub.counted")
    try:
        kv.put(payload)
    except OSError:
        counter.inc()  # observable: not flagged


def not_a_seam(kv, payload):
    try:
        kv.put(payload)
    except Exception:
        payload.dropped = True  # no seam declared: outside this class
