"""Lockcheck fixture: off-lock read and write of a guarded attribute."""

import threading


class Table:
    _GUARDED_BY = {"_items": "_lock", "_count": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}
        self._count = 0

    def good_put(self, key, value):
        with self._lock:
            self._items[key] = value
            self._count += 1

    def bad_write(self, key, value):
        self._items[key] = value  # VIOLATION: off-lock write

    def bad_read(self):
        return self._count  # VIOLATION: off-lock read

    def good_snapshot(self):
        with self._lock:
            return dict(self._items), self._count
