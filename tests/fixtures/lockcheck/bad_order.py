"""Lockcheck fixture: inconsistent lock acquisition order (A->B vs B->A)
plus a non-reentrant re-acquire."""

import threading


class TwoLocks:
    def __init__(self):
        self._a_lock = threading.Lock()
        self._b_lock = threading.Lock()

    def forward(self):
        with self._a_lock:
            with self._b_lock:  # order edge A -> B
                pass

    def backward(self):
        with self._b_lock:
            with self._a_lock:  # VIOLATION: order edge B -> A (cycle)
                pass

    def relock(self):
        with self._a_lock:
            with self._a_lock:  # VIOLATION: non-reentrant re-acquire
                pass
