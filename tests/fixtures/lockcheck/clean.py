"""Lockcheck fixture: a fully disciplined class — zero findings.

Exercises every convention: _GUARDED_BY dict, a trailing guarded_by
comment, a requires-annotated helper, an internally-synced member, linear
acquire/release, and a thread target touching only guarded state.
"""

import queue
import threading


class Clean:
    _GUARDED_BY = {
        "_items": "_lock",
        "_count": "_lock",
        "_q": "<internal>",
    }

    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}
        self._count = 0
        self._q = queue.Queue()
        self._seen = set()  # guarded_by: _lock
        self._stop_evt = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    # requires: _lock
    def _bump(self):
        self._count += 1

    def put(self, key, value):
        with self._lock:
            self._items[key] = value
            self._seen.add(key)
            self._bump()
        self._q.put(key)

    def manual(self):
        self._lock.acquire()
        try:
            return dict(self._items)
        finally:
            self._lock.release()

    def _loop(self):
        while not self._stop_evt.wait(0.1):
            with self._lock:
                n = self._count
            if n:
                self._q.put(n)
