"""Lockcheck fixture: blocking calls made while holding a lock."""

import threading
import time


class Publisher:
    def __init__(self):
        self._lock = threading.Lock()
        self._payload = {}
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        pass

    def bad_sleep(self):
        with self._lock:
            time.sleep(1.0)  # VIOLATION: sleep under lock

    def bad_join(self):
        with self._lock:
            self._thread.join(timeout=5)  # VIOLATION: thread join under lock

    def bad_indirect(self, fn):
        with self._lock:
            fn(time.sleep)  # VIOLATION: blocking callable handed to an
            return None     # invoker (the _translate_failure(x) idiom)

    def good_sleep(self):
        time.sleep(0.0)
