"""Lockcheck fixture: a `# requires:` helper called without the lock."""

import threading


class Helper:
    _GUARDED_BY = {"_table": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._table = {}

    # requires: _lock
    def _evict_one(self):
        if self._table:
            self._table.popitem()

    def good_call(self):
        with self._lock:
            self._evict_one()

    def bad_call(self):
        self._evict_one()  # VIOLATION: requires _lock, called without it
