"""Lockcheck fixture: a thread target touching shared mutable state with
no _GUARDED_BY annotation at all."""

import threading


class Worker:
    def __init__(self):
        self._state = 0
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            self._state += 1  # VIOLATION: unannotated shared attribute

    def read_state(self):
        return self._state
