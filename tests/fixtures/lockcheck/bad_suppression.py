"""Lockcheck fixture: a stale suppression (excusing nothing) and a
suppression without a reason."""

import threading


class Sup:
    _GUARDED_BY = {"_value": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0

    def fine(self):
        # lockcheck: ignore[old excuse for code that was since fixed]
        with self._lock:  # STALE: the access below is properly locked now
            return self._value

    def reasonless(self):
        return self._value  # lockcheck: ignore
