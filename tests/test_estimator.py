"""Estimator + Store tests (parity targets: spark/common/store.py layout and
spark/torch/remote.py per-epoch train/validate/checkpoint/resume loop,
exercised here without Spark on the single-process world)."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from horovod_tpu.estimator import Estimator
from horovod_tpu.store import LocalStore, Store
from horovod_tpu.models.mlp import init_mlp, mlp_forward, softmax_cross_entropy


def _make_estimator(store, epochs=2, run_id="run1"):
    return Estimator(
        init_fn=lambda rng: init_mlp(rng, sizes=(8, 16, 3)),
        forward_fn=mlp_forward,
        loss_fn=lambda p, x, y: softmax_cross_entropy(mlp_forward(p, x), y),
        optimizer=optax.adam(1e-2),
        store=store, run_id=run_id, epochs=epochs, batch_size=16,
        metric_fns={"acc": lambda p, x, y: jnp.mean(
            (jnp.argmax(mlp_forward(p, x), axis=1) == y).astype(jnp.float32))},
    )


def _data(n=256, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 8).astype(np.float32)
    y = (x.sum(axis=1) > 4).astype(np.int32) + (x[:, 0] > 0.5)
    return x, y.astype(np.int32)


def test_store_checkpoint_roundtrip(tmp_path):
    store = Store.create(str(tmp_path / "store"))
    assert isinstance(store, LocalStore)
    tree = {"a": np.arange(6, dtype=np.float32).reshape(2, 3),
            "b": [np.float64(3.5), np.int32(7)]}
    store.save_checkpoint("r", 0, tree)
    store.save_checkpoint("r", 3, tree)
    assert store.latest_checkpoint_step("r") == 3
    assert store.checkpoint_steps("r") == [0, 3]
    out = store.load_checkpoint("r")
    np.testing.assert_array_equal(out["a"], tree["a"])
    assert float(out["b"][0]) == 3.5 and int(out["b"][1]) == 7


def test_store_scheme_routing(tmp_path):
    from horovod_tpu.store import RemoteStore
    assert isinstance(Store.create(f"file://{tmp_path}/s"), LocalStore)
    assert isinstance(Store.create("memory://route-test"), RemoteStore)


def test_estimator_fit_and_predict(tmp_path):
    store = Store.create(str(tmp_path / "store"))
    est = _make_estimator(store, epochs=2)
    x, y = _data()
    model = est.fit((x, y), val_data=(x, y))
    assert len(model.history) == 2
    assert model.history[0]["train_loss"] > 0
    assert "val_acc" in model.history[0]
    # training reduced the loss
    assert model.history[-1]["train_loss"] <= model.history[0]["train_loss"]
    preds = model.predict(x[:10])
    assert preds.shape == (10, 3)
    # checkpoints were written per epoch
    assert store.checkpoint_steps("run1") == [0, 1]


def test_estimator_resume(tmp_path):
    store = Store.create(str(tmp_path / "store"))
    x, y = _data()
    _make_estimator(store, epochs=1, run_id="r2").fit((x, y))
    assert store.latest_checkpoint_step("r2") == 0
    # second fit with more epochs resumes from epoch 1 (not from scratch)
    model = _make_estimator(store, epochs=3, run_id="r2").fit((x, y))
    assert [h["epoch"] for h in model.history] == [1, 2]
    assert store.checkpoint_steps("r2") == [0, 1, 2]


def test_remote_store_roundtrip():
    """Store.create routes scheme:// prefixes to the fsspec RemoteStore
    (reference HDFSStore role, spark/common/store.py:256); memory:// gives a
    hermetic fake remote filesystem."""
    from horovod_tpu.store import RemoteStore

    st = Store.create("memory://ckpt-roundtrip")
    assert isinstance(st, RemoteStore)
    tree = {"w": np.arange(6.0).reshape(2, 3),
            "opt": [np.float32(2.5), np.zeros(4)]}
    st.save_checkpoint("runA", 1, tree)
    st.save_checkpoint("runA", 5, tree)
    assert st.latest_checkpoint_step("runA") == 5
    assert st.checkpoint_steps("runA") == [1, 5]
    back = st.load_checkpoint("runA", step=1)
    np.testing.assert_array_equal(back["w"], tree["w"])
    assert float(back["opt"][0]) == 2.5
    assert st.load_checkpoint("missing-run") is None


def test_estimator_with_remote_store():
    """The estimator trains, checkpoints, and resumes against a
    RemoteStore — the preemptible-VM elastic checkpointing path."""
    st = Store.create("memory://est-remote")
    x, y = _data()
    model = _make_estimator(st, epochs=2, run_id="rr").fit((x, y))
    assert len(model.history) == 2
    assert st.checkpoint_steps("rr") == [0, 1]
    # resume picks up from the stored checkpoint
    model2 = _make_estimator(st, epochs=3, run_id="rr").fit((x, y))
    assert [h["epoch"] for h in model2.history] == [2]


def _sharded_worker():
    import os
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax
    import horovod_tpu as hvd
    from horovod_tpu.data import ShardedNpzDataset
    from horovod_tpu.estimator import Estimator
    from horovod_tpu.models.mlp import (init_mlp, mlp_forward,
                                        softmax_cross_entropy)

    ds = ShardedNpzDataset(os.environ["TEST_SHARD_DIR"])
    est = Estimator(
        init_fn=lambda rng: init_mlp(rng, sizes=(8, 16, 3)),
        forward_fn=mlp_forward,
        loss_fn=lambda p, x, y: softmax_cross_entropy(mlp_forward(p, x), y),
        optimizer=optax.sgd(0.05), store=None, epochs=2, batch_size=16,
        shuffle=False)
    model = est.fit(ds)
    # digest of the final replica: after an uneven epoch the estimator must
    # have re-synced every rank from the last-joined rank, so these match
    digest = float(sum(float(jnp.sum(leaf))
                       for leaf in jax.tree_util.tree_leaves(model.params)))
    return {"rank": hvd.rank(), "epochs": len(model.history),
            "losses_finite": all(np.isfinite(h["train_loss"])
                                 for h in model.history),
            "params_digest": digest,
            "params": [np.asarray(l)
                       for l in jax.tree_util.tree_leaves(model.params)]}


@pytest.mark.integration
def test_estimator_uneven_shards_join(tmp_path):
    """VERDICT r2 item 6: an on-disk sharded dataset with UNEVEN per-rank
    sample counts trains to completion — the ragged tail flows through
    join() instead of deadlocking or dropping data."""
    from horovod_tpu.data import ShardedNpzDataset
    from horovod_tpu.runner import run

    rng = np.random.RandomState(0)
    x = rng.rand(150, 8).astype(np.float32)
    y = rng.randint(0, 3, size=(150,)).astype(np.int32)
    # 3 shards -> rank 0 gets shards {0, 2} (100 samples = 7 batches of 16),
    # rank 1 gets shard {1} (50 samples = 4 batches): genuinely ragged
    ShardedNpzDataset.write_shards(str(tmp_path / "shards"), x, y, 3)
    results = run(_sharded_worker, np=2, env={
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "HOROVOD_STALL_CHECK_DISABLE": "1",
        "TEST_SHARD_DIR": str(tmp_path / "shards"),
    })
    for r in results:
        assert r["epochs"] == 2, r
        assert r["losses_finite"], r
    # ADVICE r3 (high): replicas must NOT diverge after uneven epochs — the
    # estimator re-broadcasts params/opt_state from the last-joined rank
    for a, b in zip(results[0]["params"], results[1]["params"]):
        np.testing.assert_array_equal(a, b)


def test_sharded_npz_dataset_roundtrip(tmp_path):
    from horovod_tpu.data import ShardedNpzDataset
    x = np.arange(20.0).reshape(10, 2)
    y = np.arange(10)
    ds = ShardedNpzDataset.write_shards(str(tmp_path / "s"), x, y, 4)
    assert len(ds) == 4
    x0, y0 = ds.shard_arrays(0, 2)   # shards 0, 2
    x1, y1 = ds.shard_arrays(1, 2)   # shards 1, 3
    got = np.sort(np.concatenate([y0, y1]))
    np.testing.assert_array_equal(got, y)
    # more ranks than shards: empty shard with right dtype/shape
    xe, ye = ds.shard_arrays(5, 6)
    assert xe.shape == (0, 2) and len(ye) == 0


def test_shard_batch_iterator_streams_bounded(tmp_path):
    """VERDICT r3 item 6: the streaming reader covers every sample exactly
    once per epoch with batches crossing shard boundaries, reshuffles per
    epoch, and never holds more than prefetch+1 shards in RAM — the dataset
    (12 shards) is far larger than the buffer (prefetch=1 -> <=2 resident)."""
    from horovod_tpu.data import ShardedNpzDataset
    x = np.arange(120.0).reshape(60, 2)
    y = np.arange(60)
    ds = ShardedNpzDataset.write_shards(str(tmp_path / "s"), x, y, 12)

    it = ds.iter_batches(0, 1, batch_size=8, shuffle=True, seed=0, prefetch=1)
    batches = list(it)
    got = np.sort(np.concatenate([b[1] for b in batches]))
    np.testing.assert_array_equal(got, y)            # exact coverage
    assert [len(b[1]) for b in batches] == [8] * 7 + [4]  # cross-shard + tail
    # queue(1) + loader in-hand(1) + consumer current(1), regardless of
    # loader/consumer race timing
    assert it.max_resident_shards <= 3, it.max_resident_shards

    # per-epoch reshuffle: different seed -> different order, same coverage
    e2 = [b[1] for b in ds.iter_batches(0, 1, 8, shuffle=True, seed=1)]
    assert not all(np.array_equal(a[1], b)
                   for a, b in zip(batches, e2))
    np.testing.assert_array_equal(np.sort(np.concatenate(e2)), y)

    # two ranks: disjoint, complete
    r0 = np.concatenate([b[1] for b in ds.iter_batches(0, 2, 8, seed=0)])
    r1 = np.concatenate([b[1] for b in ds.iter_batches(1, 2, 8, seed=0)])
    np.testing.assert_array_equal(np.sort(np.concatenate([r0, r1])), y)

    # more ranks than shards: empty iterator
    assert list(ds.iter_batches(15, 16, 8)) == []


def test_estimator_streams_dataset_larger_than_buffer(tmp_path):
    """The estimator trains from a sharded dataset without ever loading a
    rank's whole partition (shard_arrays is NOT called; residency stays at
    the prefetch bound)."""
    from horovod_tpu import data as data_mod

    x, y = _data(n=240)
    ds = data_mod.ShardedNpzDataset.write_shards(str(tmp_path / "s"), x, y, 16)
    seen = {}
    orig = data_mod.ShardedNpzDataset.iter_batches

    def spy(self, *a, **kw):
        it = orig(self, *a, **kw)
        seen["it"] = it
        return it

    data_mod.ShardedNpzDataset.iter_batches = spy
    try:
        model = _make_estimator(None, epochs=2).fit(ds)
    finally:
        data_mod.ShardedNpzDataset.iter_batches = orig
    assert len(model.history) == 2
    assert all(np.isfinite(h["train_loss"]) for h in model.history)
    assert seen["it"].max_resident_shards <= 4   # prefetch(2) + 2
