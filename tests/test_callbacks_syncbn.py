"""SyncBatchNorm + training callbacks tests.

Reference models: torch/sync_batch_norm.py (stat merge math),
_keras/callbacks.py (LR schedule/warmup, metric averaging, broadcast).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.callbacks import (BestModelCheckpoint, BroadcastGlobalVariablesCallback,
                                   CallbackList, LearningRateScheduleCallback,
                                   LearningRateWarmupCallback,
                                   MetricAverageCallback, TrainLoopState)
from horovod_tpu.ops.sync_batch_norm import SyncBatchNorm, sync_batch_stats


@pytest.fixture(scope="module", autouse=True)
def init_hvd():
    hvd.init()
    yield


class TestSyncBatchStats:
    def test_matches_global_batch(self, mesh8):
        """Stats psum'd over 8 shards == stats of the unsharded batch."""
        from jax import shard_map
        rng = np.random.RandomState(0)
        x = rng.rand(16, 4).astype(np.float32) * 3 + 1
        garr = jax.device_put(jnp.asarray(x),
                              NamedSharding(mesh8, P("world")))

        def body(blk):
            m, v = sync_batch_stats(blk, "world", (0,))
            return m[None], v[None]

        fn = jax.jit(shard_map(body, mesh=mesh8, in_specs=P("world"),
                               out_specs=(P("world"), P("world"))))
        mean, var = fn(garr)
        np.testing.assert_allclose(np.asarray(mean)[0], x.mean(0), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(var)[0], x.var(0),
                                   rtol=1e-4, atol=1e-5)


class TestSyncBatchNormModule:
    def test_normalizes_and_tracks_stats(self):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.rand(32, 8).astype(np.float32) * 5 - 2)
        bn = SyncBatchNorm(use_running_average=False, axis_name=None,
                           momentum=0.5)
        variables = bn.init(jax.random.PRNGKey(0), x)
        y, mutated = bn.apply(variables, x, mutable=["batch_stats"])
        y = np.asarray(y)
        np.testing.assert_allclose(y.mean(0), 0.0, atol=1e-4)
        np.testing.assert_allclose(y.std(0), 1.0, atol=1e-2)
        # running stats moved toward batch stats
        rm = np.asarray(mutated["batch_stats"]["mean"])
        assert not np.allclose(rm, 0.0)

    def test_inference_uses_running_stats(self):
        x = jnp.ones((4, 8), jnp.float32)
        bn = SyncBatchNorm(use_running_average=True)
        variables = bn.init(jax.random.PRNGKey(0), x)
        y = bn.apply(variables, x)
        # running mean=0, var=1 at init → y == x (scale=1, bias=0)
        np.testing.assert_allclose(np.asarray(y), np.asarray(x), atol=1e-5)

    def test_cross_replica_inside_shard_map(self, mesh8):
        """Each shard normalizes with GLOBAL statistics."""
        from jax import shard_map
        rng = np.random.RandomState(2)
        x = rng.rand(16, 8).astype(np.float32)
        # make shard means very different so local-only BN would differ
        x[:8] += 10.0
        garr = jax.device_put(jnp.asarray(x),
                              NamedSharding(mesh8, P("world")))
        bn = SyncBatchNorm(use_running_average=False, axis_name="world")
        variables = bn.init(jax.random.PRNGKey(0), x[:2])

        def body(blk):
            y, _ = bn.apply(variables, blk, mutable=["batch_stats"])
            return y

        fn = jax.jit(shard_map(body, mesh=mesh8, in_specs=P("world"),
                               out_specs=P("world")))
        y = np.asarray(fn(garr))
        expected = (x - x.mean(0)) / np.sqrt(x.var(0) + 1e-5)
        np.testing.assert_allclose(y, expected, rtol=1e-3, atol=1e-3)


class TestCallbacks:
    def test_lr_warmup_ramp(self):
        cb = LearningRateWarmupCallback(warmup_epochs=4, size=8)
        state = TrainLoopState()
        scales = []
        for epoch in range(6):
            state.epoch = epoch
            cb.on_epoch_begin(state)
            scales.append(state.lr_scale)
        assert scales[0] == pytest.approx(1.0 / 8)
        assert scales[4] == pytest.approx(1.0)
        assert all(b >= a for a, b in zip(scales, scales[1:]))

    def test_lr_schedule_staircase(self):
        cb = LearningRateScheduleCallback(
            multiplier=lambda e: 0.1 ** (e // 2), start_epoch=0)
        state = TrainLoopState()
        state.epoch = 3
        cb.on_epoch_begin(state)
        assert state.lr_scale == pytest.approx(0.1)

    def test_metric_average_single_rank(self):
        cb = MetricAverageCallback()
        logs = {"loss": 2.0}
        cb.on_epoch_end(TrainLoopState(), logs)
        assert logs["loss"] == 2.0

    def test_broadcast_and_checkpoint(self, tmp_path):
        params = {"w": jnp.full((3,), 2.0)}
        state = TrainLoopState(params=params)
        CallbackList([BroadcastGlobalVariablesCallback(0)]).on_train_begin(state)
        np.testing.assert_allclose(np.asarray(state.params["w"]), 2.0)

        ckpt = BestModelCheckpoint(str(tmp_path / "best.pkl"),
                                   monitor="loss", mode="min")
        ckpt.on_epoch_end(state, {"loss": 1.0})
        ckpt.on_epoch_end(state, {"loss": 2.0})  # no improvement
        import pickle
        with open(tmp_path / "best.pkl", "rb") as f:
            saved = pickle.load(f)
        assert saved["loss"] == 1.0


class TestScaledLRUnderJit:
    """The LR schedule/warmup callbacks must affect a JITTED train step with
    no re-trace (VERDICT r1 weak #6: a trace-time closure silently does
    nothing under jit)."""

    def test_scale_changes_updates_without_retrace(self):
        import jax
        import jax.numpy as jnp
        import optax
        from horovod_tpu import callbacks as cb

        opt = cb.scaled_lr(optax.sgd(1.0))
        params = {"w": jnp.ones(3)}
        opt_state = opt.init(params)
        traces = 0

        @jax.jit
        def step(params, opt_state, grad_scale):
            nonlocal traces
            traces += 1
            grads = {"w": jnp.ones(3) * grad_scale}
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        p1, opt_state = step(params, opt_state, 1.0)
        np.testing.assert_allclose(np.asarray(p1["w"]), 0.0, atol=1e-6)
        # halve the LR via the functional setter — same structure, no re-jit
        opt_state = cb.set_lr_scale(opt_state, 0.5)
        p2, opt_state = step(p1, opt_state, 1.0)
        np.testing.assert_allclose(np.asarray(p2["w"]), -0.5, atol=1e-6)
        assert traces == 1, "set_lr_scale must not trigger recompilation"

    def test_schedule_callback_drives_jitted_step(self):
        import jax
        import jax.numpy as jnp
        import optax
        from horovod_tpu import callbacks as cb

        opt = cb.scaled_lr(optax.sgd(1.0))
        params = {"w": jnp.zeros(())}
        state = cb.TrainLoopState(params=params, opt_state=opt.init(params))
        sched = cb.LearningRateScheduleCallback(
            multiplier=lambda epoch: 10.0 ** -epoch)

        @jax.jit
        def step(params, opt_state):
            updates, opt_state = opt.update({"w": jnp.ones(())}, opt_state,
                                            params)
            return optax.apply_updates(params, updates), opt_state

        deltas = []
        for epoch in range(3):
            state.epoch = epoch
            sched.on_epoch_begin(state)
            before = float(np.asarray(state.params["w"]))
            state.params, state.opt_state = step(state.params,
                                                 state.opt_state)
            deltas.append(before - float(np.asarray(state.params["w"])))
        np.testing.assert_allclose(deltas, [1.0, 0.1, 0.01], rtol=1e-5)

    def test_warmup_callback_ramps_under_jit(self):
        import jax
        import jax.numpy as jnp
        import optax
        from horovod_tpu import callbacks as cb

        opt = cb.scaled_lr(optax.sgd(1.0))
        params = {"w": jnp.zeros(())}
        state = cb.TrainLoopState(params=params, opt_state=opt.init(params))
        warm = cb.LearningRateWarmupCallback(warmup_epochs=2.0, size=4)

        @jax.jit
        def step(params, opt_state):
            updates, opt_state = opt.update({"w": jnp.ones(())}, opt_state,
                                            params)
            return optax.apply_updates(params, updates), opt_state

        scales = []
        for epoch in range(3):
            state.epoch = epoch
            warm.on_epoch_begin(state)
            before = float(np.asarray(state.params["w"]))
            state.params, state.opt_state = step(state.params,
                                                 state.opt_state)
            scales.append(round(before - float(np.asarray(state.params["w"])),
                                5))
        # epoch 0: 1/4; epoch 1: (1/4)(1*3/2+1)=0.625; epoch 2: full 1.0
        np.testing.assert_allclose(scales, [0.25, 0.625, 1.0], rtol=1e-4)
