"""Bucket-pipelined comm/compute overlap + ZeRO-1 all-gather prefetch
(ISSUE 6).

Runs on the size-1 eager world (one process): the collective math is
identity there, so every assertion checks the overlap plumbing — mode
resolution, the split rs->update / prefetched-all-gather launch pair, the
staged replay pipeline, dispatch accounting, world-version invalidation —
and trajectory parity against the serial (overlap off) path, which must be
BITWISE (same math, different schedule). Multi-participant wire behavior
of the same builders is covered by tests/test_compiled_structure.py (IR
structure) and tests/test_multiprocess.py (np=2 parity across an elastic
world-version bump).
"""

import os
import subprocess
import sys

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import horovod_tpu as hvd
from horovod_tpu import faults
from horovod_tpu import metrics as hvd_metrics
from horovod_tpu.common.exceptions import HorovodInternalError


def _ctr(name):
    return hvd_metrics.counter_total(hvd_metrics.snapshot(), name)


@pytest.fixture()
def engine():
    hvd.init()
    eng = hvd._engine()
    prev = (eng.config.step_replay_warmup, eng.config.step_replay,
            eng.config.overlap_pipeline, eng.config.zero1_prefetch,
            eng.config.fusion_threshold_bytes)
    eng.config.step_replay_warmup = 2
    eng.config.step_replay = True
    eng.replay.invalidate_all("test isolation")
    yield eng
    eng.replay.invalidate_all("test isolation")
    (eng.config.step_replay_warmup, eng.config.step_replay,
     eng.config.overlap_pipeline, eng.config.zero1_prefetch,
     eng.config.fusion_threshold_bytes) = prev
    os.environ.pop("HOROVOD_TPU_WORLD_VERSION", None)


def _sharded_run(engine, mode, steps=6, lr=0.1, prefetch=True):
    """Run ``steps`` ZeRO-1 sharded optimizer steps under ``mode`` from a
    fixed start; returns the final params."""
    import optax
    from horovod_tpu.optimizer import DistributedEagerOptimizer
    engine.config.overlap_pipeline = mode
    engine.config.zero1_prefetch = prefetch
    engine.replay.invalidate_all(f"mode -> {mode}")
    params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
    opt = DistributedEagerOptimizer(optax.sgd(lr, momentum=0.9),
                                    sharded=True)
    state = opt.init(params)

    def loss(p, x):
        return jnp.sum((x @ p["w"] + p["b"]) ** 2)

    grad_fn = jax.jit(jax.grad(loss))
    x = jnp.ones((2, 4))
    for _ in range(steps):
        params, state = opt.update_and_apply(grad_fn(params, x), state,
                                             params)
    jax.block_until_ready(params["w"])
    return params


def test_overlap_mode_resolution(engine, monkeypatch):
    """_overlap_mode: explicit modes pass through; "auto" picks interleave
    on a size-1 world (staged sub-launches cannot overlap anything without
    peers) and respects the stage-bytes threshold; Join-live worlds demote
    staged to interleave on EVERY resolution path (forced or auto), so the
    eager warmup split and replay's armed program always agree."""
    cfg = engine.config
    cfg.overlap_pipeline = "off"
    assert engine._overlap_mode(1 << 30, 8, True) == "off"
    cfg.overlap_pipeline = "staged"
    assert engine._overlap_mode(0, 1) == "staged"
    cfg.overlap_pipeline = "interleave"
    assert engine._overlap_mode(1 << 30, 8) == "interleave"
    cfg.overlap_pipeline = "auto"
    # size-1 world: staged gains nothing, auto stays single-launch
    assert engine._overlap_mode(1 << 30, 8, True) == "interleave"
    # Join-live world with peers: staged demotes, forced or auto
    monkeypatch.setattr(engine.backend, "size", lambda: 2)
    prev_join = cfg.join_enabled
    try:
        cfg.join_enabled = True
        cfg.overlap_pipeline = "staged"
        assert engine._overlap_mode(1 << 30, 8, True) == "interleave"
        cfg.overlap_pipeline = "auto"
        assert engine._overlap_mode(1 << 30, 8, True) == "interleave"
        cfg.join_enabled = False
        assert engine._overlap_mode(1 << 30, 8, True) == "staged"
        cfg.overlap_pipeline = "staged"
        assert engine._overlap_mode(0, 1) == "staged"
    finally:
        cfg.join_enabled = prev_join


def test_grouped_allreduce_pipelined_parity(engine):
    """The pipelined grouped program must be value-identical to the serial
    one (same math, different trace order) — bitwise, since the schedule
    change reorders no arithmetic."""
    from horovod_tpu.common.reduce_ops import ReduceOp
    rng = np.random.RandomState(0)
    tensors = [jnp.asarray(rng.randn(5, 3).astype(np.float32)),
               jnp.asarray(rng.randn(17).astype(np.float32)),
               jnp.asarray(rng.randn(2, 2).astype(np.float32))]
    outs = {}
    for mode in ("off", "interleave"):
        engine.config.overlap_pipeline = mode
        hs = engine.grouped_allreduce(list(tensors), name=f"par.{mode}",
                                      op=ReduceOp.SUM)
        outs[mode] = [np.asarray(h.synchronize()) for h in hs]
    for a, b in zip(outs["off"], outs["interleave"]):
        assert np.array_equal(a, b)


def test_sharded_prefetch_trajectory_bitwise_equal(engine):
    """The tentpole parity bar: the split rs->update + prefetched
    all-gather trajectory is BITWISE equal to the serial fused step (the
    schedule moves launches, never arithmetic). The split leg rides the
    STAGED schedule only — under "auto" on this size-1 world the mode
    resolves to "interleave" and the all-gather stays inside the fused
    program (no warmup-only legs that would vanish once replay arms)."""
    p_off = _sharded_run(engine, "off", prefetch=False)
    legs0 = _ctr("hvd_tpu_overlap_prefetch_total")
    p_auto = _sharded_run(engine, "auto")
    for a, b in zip(jax.tree_util.tree_leaves(p_off),
                    jax.tree_util.tree_leaves(p_auto)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    assert _ctr("hvd_tpu_overlap_prefetch_total") == legs0, \
        "auto resolved interleave: the fused program must not split legs"
    assert not engine._zero1_prefetch
    p_staged = _sharded_run(engine, "staged")
    for a, b in zip(jax.tree_util.tree_leaves(p_off),
                    jax.tree_util.tree_leaves(p_staged)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    assert _ctr("hvd_tpu_overlap_prefetch_total") > legs0, \
        "no prefetch leg was launched on the staged path"


def test_staged_replay_sharded_two_launch_steady_state(engine):
    """Forced "staged" mode: a steady-state replayed sharded step is
    exactly TWO engine dispatches — the rs->shard-update launch and the
    held all-gather prefetch leg — and each steady step holds a new leg."""
    p_off = _sharded_run(engine, "off", prefetch=False)
    legs0 = _ctr("hvd_tpu_overlap_prefetch_total")
    import optax
    from horovod_tpu.optimizer import DistributedEagerOptimizer
    engine.config.overlap_pipeline = "staged"
    engine.config.zero1_prefetch = True
    engine.replay.invalidate_all("staged test")
    params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
    opt = DistributedEagerOptimizer(optax.sgd(0.1, momentum=0.9),
                                    sharded=True)
    state = opt.init(params)

    def loss(p, x):
        return jnp.sum((x @ p["w"] + p["b"]) ** 2)

    grad_fn = jax.jit(jax.grad(loss))
    x = jnp.ones((2, 4))
    for _ in range(4):   # warmup 2 + arm + 1 replayed
        params, state = opt.update_and_apply(grad_fn(params, x), state,
                                             params)
    replayed0 = engine.replay.replayed_steps
    g = grad_fn(params, x)
    jax.block_until_ready(g["w"])
    d0 = engine.dispatch_count
    params, state = opt.update_and_apply(g, state, params)
    assert engine.replay.replayed_steps == replayed0 + 1
    assert engine.dispatch_count - d0 == 2, \
        "a staged replayed sharded step must be zupd + zag launches"
    inval0 = _ctr("hvd_tpu_overlap_prefetch_invalidations_total")
    for _ in range(2):
        params, state = opt.update_and_apply(grad_fn(params, x), state,
                                             params)
    jax.block_until_ready(params["w"])
    assert _ctr("hvd_tpu_overlap_prefetch_total") - legs0 >= 3
    # exactly ONE row held between steps (the latest leg), and steady
    # reuse retires rows WITHOUT counting invalidations — the counter only
    # sees legs genuinely dropped before reuse
    assert len(engine._zero1_prefetch) == 1
    assert _ctr("hvd_tpu_overlap_prefetch_invalidations_total") == inval0
    # staged trajectory == serial trajectory, bitwise (2 extra steps run
    # under staged, so only compare against a same-length serial run)
    p_staged = _sharded_run(engine, "staged")
    for a, b in zip(jax.tree_util.tree_leaves(p_off),
                    jax.tree_util.tree_leaves(p_staged)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_staged_replay_honors_prefetch_disabled(engine):
    """HOROVOD_TPU_ZERO1_PREFETCH=0 under forced "staged" mode: the armed
    sharded segment stays ONE fused rs->update->ag sub-launch (no zag
    stage, no held leg) — the documented knob contract holds through
    replay, not just the eager warmup path."""
    p_off = _sharded_run(engine, "off", prefetch=False)
    legs0 = _ctr("hvd_tpu_overlap_prefetch_total")
    import optax
    from horovod_tpu.optimizer import DistributedEagerOptimizer
    engine.config.overlap_pipeline = "staged"
    engine.config.zero1_prefetch = False
    engine.replay.invalidate_all("staged no-prefetch test")
    params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
    opt = DistributedEagerOptimizer(optax.sgd(0.1, momentum=0.9),
                                    sharded=True)
    state = opt.init(params)
    grad_fn = jax.jit(jax.grad(
        lambda p, x: jnp.sum((x @ p["w"] + p["b"]) ** 2)))
    x = jnp.ones((2, 4))
    for _ in range(4):
        params, state = opt.update_and_apply(grad_fn(params, x), state,
                                             params)
    g = grad_fn(params, x)
    jax.block_until_ready(g["w"])
    replayed0 = engine.replay.replayed_steps
    d0 = engine.dispatch_count
    params, state = opt.update_and_apply(g, state, params)
    assert engine.replay.replayed_steps == replayed0 + 1
    assert engine.dispatch_count - d0 == 1, \
        "prefetch off: the staged sharded step must stay one fused launch"
    assert _ctr("hvd_tpu_overlap_prefetch_total") == legs0, \
        "prefetch off but a leg was launched"
    assert not engine._zero1_prefetch
    for _ in range(1):
        params, state = opt.update_and_apply(grad_fn(params, x), state,
                                             params)
    jax.block_until_ready(params["w"])
    p_ref = _sharded_run(engine, "off", prefetch=False)
    del p_ref  # same-length serial rerun keeps the comparison honest
    for a, b in zip(jax.tree_util.tree_leaves(p_off),
                    jax.tree_util.tree_leaves(
                        _sharded_run(engine, "staged", prefetch=False))):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_prefetch_invalidates_on_world_version_bump(engine):
    """A held prefetch leg must not survive an elastic world-version bump
    — and the bump must invalidate, not poison: stepping continues and
    the trajectory stays bitwise equal to the serial path."""
    import optax
    from horovod_tpu.optimizer import DistributedEagerOptimizer
    p_ref = _sharded_run(engine, "off", steps=8, prefetch=False)
    engine.config.overlap_pipeline = "staged"   # legs ride the staged schedule
    engine.config.zero1_prefetch = True
    engine.replay.invalidate_all("bump test")
    params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
    opt = DistributedEagerOptimizer(optax.sgd(0.1, momentum=0.9),
                                    sharded=True)
    state = opt.init(params)

    def loss(p, x):
        return jnp.sum((x @ p["w"] + p["b"]) ** 2)

    grad_fn = jax.jit(jax.grad(loss))
    x = jnp.ones((2, 4))
    for _ in range(4):
        params, state = opt.update_and_apply(grad_fn(params, x), state,
                                             params)
    assert engine._zero1_prefetch, "no leg held before the bump"
    inval0 = _ctr("hvd_tpu_overlap_prefetch_invalidations_total")
    os.environ["HOROVOD_TPU_WORLD_VERSION"] = str(engine.world_version + 3)
    for _ in range(4):
        params, state = opt.update_and_apply(grad_fn(params, x), state,
                                             params)
    jax.block_until_ready(params["w"])
    assert _ctr("hvd_tpu_overlap_prefetch_invalidations_total") > inval0
    for a, b in zip(jax.tree_util.tree_leaves(p_ref),
                    jax.tree_util.tree_leaves(params)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.chaos
def test_overlap_prefetch_failpoint_raises_cleanly(engine):
    """overlap.prefetch armed with raise(): the prefetch launch failure
    surfaces as HorovodInternalError (what the elastic loop recovers
    from), and the NEXT step succeeds — injection must not poison the
    engine or the held-leg registry."""
    import optax
    from horovod_tpu.optimizer import DistributedEagerOptimizer
    engine.config.overlap_pipeline = "staged"   # legs ride the staged schedule
    engine.config.zero1_prefetch = True
    engine.replay.invalidate_all("failpoint test")
    params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
    opt = DistributedEagerOptimizer(optax.sgd(0.1), sharded=True)
    state = opt.init(params)
    grad_fn = jax.jit(jax.grad(
        lambda p, x: jnp.sum((x @ p["w"] + p["b"]) ** 2)))
    x = jnp.ones((2, 4))
    faults.arm("overlap.prefetch=1*raise(HorovodInternalError)")
    try:
        with pytest.raises(HorovodInternalError):
            opt.update_and_apply(grad_fn(params, x), state, params)
    finally:
        faults.disarm()
    params, state = opt.update_and_apply(grad_fn(params, x), state, params)
    jax.block_until_ready(params["w"])
    assert bool(np.isfinite(np.asarray(params["w"])).all())


@pytest.mark.perf
def test_perf_smoke_pipelined_step_one_iteration(engine):
    """Tier-1-safe perf smoke (ISSUE 6 CI satellite): build the pipelined
    replay step and run it ONE iteration on the CPU world — no timing
    assertions, just that the overlap-mode programs build, launch, and
    produce the serial path's values."""
    from horovod_tpu.common.reduce_ops import ReduceOp
    rng = np.random.RandomState(7)
    tensors = [jnp.asarray(rng.randn(8, 2).astype(np.float32))
               for _ in range(6)]
    engine.config.overlap_pipeline = "interleave"
    engine.config.fusion_threshold_bytes = 48  # force multiple buckets
    engine.replay.invalidate_all("perf smoke")
    out = None
    for i in range(3):   # 2 warmup + 1 replayed pipelined iteration
        engine.step_begin()
        hs = engine.grouped_allreduce(list(tensors), name=f"perf.{i}",
                                      op=ReduceOp.SUM)
        out = [np.asarray(h.synchronize()) for h in hs]
        engine.step_end()
    assert engine.replay.replayed_steps >= 1
    for a, b in zip(out, tensors):
        assert np.array_equal(a, np.asarray(b))


def test_apply_xla_lhs_noop_when_backend_live():
    """In-process: a live jax backend means the flag append would be
    silently ignored — apply_xla_lhs must WARN and no-op instead."""
    from horovod_tpu.common.env import apply_xla_lhs
    jax.devices()  # ensure a backend exists
    prev_flags = os.environ.get("XLA_FLAGS")
    os.environ["HOROVOD_TPU_XLA_LHS"] = "1"
    try:
        assert apply_xla_lhs() is False
        assert os.environ.get("XLA_FLAGS") == prev_flags
    finally:
        os.environ.pop("HOROVOD_TPU_XLA_LHS", None)


def test_apply_xla_lhs_appends_before_backend():
    """Fresh process, knob set, no jax import yet: the scheduler flag must
    land in XLA_FLAGS exactly once (idempotent)."""
    code = (
        "import os\n"
        "os.environ['HOROVOD_TPU_XLA_LHS'] = '1'\n"
        "from horovod_tpu.common.env import apply_xla_lhs\n"
        "assert apply_xla_lhs() is True\n"
        "flags = os.environ['XLA_FLAGS']\n"
        "assert flags.count('xla_tpu_enable_latency_hiding_scheduler') == 1\n"
        "assert apply_xla_lhs() is True  # idempotent\n"
        "assert os.environ['XLA_FLAGS'].count("
        "'xla_tpu_enable_latency_hiding_scheduler') == 1\n"
        "print('ok')\n")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=120,
                          cwd=os.path.dirname(os.path.dirname(
                              os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stderr[-500:]
    assert "ok" in proc.stdout
