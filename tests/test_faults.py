"""Failpoint subsystem unit tests (ISSUE 4 tentpole): spec grammar, action
semantics, per-rank targeting, disabled-mode freeness, namespace lint, and
the shared retry helper."""

import logging
import threading
import time

import pytest

from horovod_tpu import faults
from horovod_tpu.common.retry import retrying
from horovod_tpu.metrics import registry


@pytest.fixture(autouse=True)
def _disarm():
    faults.disarm()
    yield
    faults.disarm()


# -- grammar ----------------------------------------------------------------

class TestGrammar:
    def test_bad_clause_shapes(self):
        for spec in ("nonsense", "test.x", "test.x=frobnicate(1)",
                     "test.x=raise()", "test.x=0*drop()",
                     "test.x=delay(xyz)", "test.x=drop(5)",
                     "BadName=drop()", "test.x=raise(NoSuchExc)"):
            with pytest.raises(ValueError):
                faults.arm(spec)
        assert not faults.enabled()

    def test_undeclared_name_rejected_at_arm(self):
        with pytest.raises(ValueError, match="FAULT_SPECS"):
            faults.arm("engine.not_a_real_point=drop()")

    def test_test_prefix_exempt(self):
        faults.arm("test.anything.goes=noop()")
        assert faults.enabled()

    def test_durations(self):
        faults.arm("test.a=delay(50ms)")
        t0 = time.monotonic()
        faults.failpoint("test.a")
        assert 0.03 < time.monotonic() - t0 < 0.5

    def test_exception_resolution_layers(self):
        import jax
        faults.arm("test.b=raise(HorovodInternalError)"
                   "->raise(JaxRuntimeError)->raise(TimeoutError)")
        from horovod_tpu.common.exceptions import HorovodInternalError
        with pytest.raises(HorovodInternalError):
            faults.failpoint("test.b")
        with pytest.raises(jax.errors.JaxRuntimeError):
            faults.failpoint("test.b")
        with pytest.raises(TimeoutError):
            faults.failpoint("test.b")
        assert faults.failpoint("test.b") is None  # exhausted


# -- action semantics -------------------------------------------------------

class TestActions:
    def test_counted_chain_then_exhaustion(self):
        faults.arm("test.c=2*raise(ConnectionError)->drop()")
        for _ in range(2):
            with pytest.raises(ConnectionError):
                faults.failpoint("test.c")
        assert faults.failpoint("test.c") is faults.DROP
        assert faults.failpoint("test.c") is None
        assert faults.hits("test.c") == 3

    def test_star_count_fires_forever(self):
        faults.arm("test.d=*drop()")
        for _ in range(10):
            assert faults.failpoint("test.d") is faults.DROP

    def test_injection_counter(self):
        ctr = registry().counter("hvd_tpu_fault_injections_total")
        before = ctr.value(name="test.e", action="noop")
        faults.arm("test.e=3*noop()")
        for _ in range(3):
            faults.failpoint("test.e")
        assert ctr.value(name="test.e", action="noop") == before + 3

    def test_per_rank_targeting(self, monkeypatch):
        faults.arm("test.f@1=*drop()")
        monkeypatch.setenv("HOROVOD_RANK", "0")
        assert faults.failpoint("test.f") is None
        monkeypatch.setenv("HOROVOD_RANK", "1")
        assert faults.failpoint("test.f") is faults.DROP

    def test_hang_broken_with_exception(self):
        from horovod_tpu.common.exceptions import HorovodInternalError
        faults.arm("test.g=hang()")
        box = {}

        def _blocked():
            try:
                faults.failpoint("test.g")
                box["out"] = "resumed"
            except Exception as e:
                box["out"] = e

        t = threading.Thread(target=_blocked, daemon=True)
        t.start()
        time.sleep(0.1)
        assert t.is_alive(), "hang() did not block"
        faults.break_hangs(HorovodInternalError("watchdog abort"))
        t.join(timeout=5)
        assert isinstance(box["out"], HorovodInternalError)

    def test_hang_with_duration_resumes(self):
        faults.arm("test.h=hang(100ms)")
        t0 = time.monotonic()
        assert faults.failpoint("test.h") is None
        assert 0.05 < time.monotonic() - t0 < 2.0

    def test_disarm_releases_parked_hangs(self):
        faults.arm("test.i=hang()")
        done = threading.Event()

        def _blocked():
            faults.failpoint("test.i")
            done.set()

        t = threading.Thread(target=_blocked, daemon=True)
        t.start()
        time.sleep(0.1)
        faults.disarm()
        assert done.wait(timeout=5), "disarm did not release the hang"

    def test_disabled_is_noop(self):
        assert not faults.enabled()
        assert faults.failpoint("engine.enqueue") is None
        assert faults.hits("engine.enqueue") == 0


# -- namespace lint (tools/check_fault_names.py, tier-1 wiring) -------------

class TestFaultNameLint:
    # NOTE (ISSUE 7): the clean-tree wiring (declared specs + call sites
    # lint-clean) moved to the unified parametrized suite in
    # tests/test_check.py (tools/check.py runs every lint); only the
    # error-path unit tests stay here next to the registry they exercise.

    def test_lint_catches_undeclared_call_site(self):
        import os
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
        from tools.check_fault_names import validate_call_sites
        errs = validate_call_sites(faults.FAULT_SPECS,
                                   [("x.py", 3, "engine.bogus")])
        assert len(errs) == 1 and "engine.bogus" in errs[0]

    def test_lint_catches_bad_declarations(self):
        import os
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
        from tools.check_fault_names import validate_specs
        errs = validate_specs({"NotKebab": "x", "test.reserved": "y",
                               "ok.name": ""})
        assert len(errs) == 3


# -- retrying() helper ------------------------------------------------------

class TestRetrying:
    def test_succeeds_after_transient_failures(self):
        reg = registry()
        retries_before = reg.counter("hvd_tpu_kv_retries_total").value(
            op="t1")
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise ConnectionError("transient")
            return "ok"

        assert retrying(flaky, attempts=5, base_delay=0.01, op="t1") == "ok"
        assert len(calls) == 3
        assert reg.counter("hvd_tpu_kv_retries_total").value(
            op="t1") == retries_before + 2

    def test_gives_up_and_counts(self):
        reg = registry()
        gave_before = reg.counter("hvd_tpu_kv_gave_up_total").value(op="t2")

        def dead():
            raise ConnectionError("down")

        with pytest.raises(ConnectionError):
            retrying(dead, attempts=3, base_delay=0.01, op="t2")
        assert reg.counter("hvd_tpu_kv_gave_up_total").value(
            op="t2") == gave_before + 1

    def test_non_retryable_propagates_immediately(self):
        calls = []

        def broken():
            calls.append(1)
            raise ValueError("bug, not weather")

        with pytest.raises(ValueError):
            retrying(broken, attempts=5, base_delay=0.01, op="t3")
        assert len(calls) == 1

    def test_deadline_bounds_attempts(self):
        calls = []

        def slow_fail():
            calls.append(1)
            raise ConnectionError("x")

        t0 = time.monotonic()
        with pytest.raises(ConnectionError):
            retrying(slow_fail, attempts=50, base_delay=0.2, max_delay=0.2,
                     jitter=0.0, deadline=0.5, op="t4")
        assert time.monotonic() - t0 < 2.0
        assert 1 <= len(calls) <= 4

    def test_backoff_schedule_shape(self):
        from horovod_tpu.common.retry import backoff_delays
        delays = list(backoff_delays(5, 0.1, 0.4, jitter=0.0))
        assert delays == [0.1, 0.2, 0.4, 0.4]
