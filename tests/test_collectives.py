"""Data-plane collective correctness on a real 8-device CPU mesh.

Mirrors the reference's exhaustive dtype x shape grids in test/test_torch.py /
test_tensorflow.py, adapted to the stacked-builder execution model: the global
array's leading axis holds each rank's tensor.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.common.reduce_ops import ReduceOp
from horovod_tpu.ops import collectives as C
from horovod_tpu.parallel.mesh import WORLD_AXIS

import ml_dtypes

DTYPES = [np.float32, np.float64, np.int32, ml_dtypes.bfloat16]


def stacked(mesh, per_rank):
    """Place a (n, *s) numpy array onto the mesh, one slice per device."""
    arr = jnp.asarray(per_rank)
    return jax.device_put(arr, NamedSharding(mesh, P(WORLD_AXIS)))


@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("shape", [(17,), (4, 5), (2, 3, 4)])
def test_allreduce_sum(mesh8, dtype, shape):
    n = 8
    rng = np.random.RandomState(0)
    data = (rng.randint(-10, 10, size=(n,) + shape)).astype(dtype)
    # float64 must actually run in float64 (with x64 off, jnp.asarray would
    # silently downcast and the case would duplicate float32)
    import contextlib
    ctx = (jax.enable_x64() if dtype == np.float64
           else contextlib.nullcontext())
    with ctx:
        fn = C.build_allreduce(mesh8, WORLD_AXIS, ReduceOp.SUM)
        garr = stacked(mesh8, data)
        assert garr.dtype == dtype, (garr.dtype, dtype)
        out = np.asarray(fn(garr)).astype(np.float64)  # replicated
    expected = data.astype(np.float64).sum(axis=0)
    np.testing.assert_allclose(out, expected,
                               rtol=2e-2 if dtype == ml_dtypes.bfloat16 else 1e-5)


@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.int32])
@pytest.mark.parametrize("op,npfn", [
    (ReduceOp.MIN, np.min), (ReduceOp.MAX, np.max), (ReduceOp.PRODUCT, np.prod)])
def test_allreduce_minmaxprod(mesh8, op, npfn, dtype):
    n = 8
    rng = np.random.RandomState(1)
    if np.issubdtype(dtype, np.integer):
        data = rng.randint(-3, 4, size=(n, 13)).astype(dtype)
    else:
        data = rng.uniform(-2, 2, size=(n, 13)).astype(dtype)
    fn = C.build_allreduce(mesh8, WORLD_AXIS, op)
    out = np.asarray(fn(stacked(mesh8, data)))
    expected = npfn(data.astype(np.float64), axis=0).astype(dtype)
    if np.issubdtype(dtype, np.integer):
        # min/max/product must be EXACT for integers (reference grids
        # include integer dtypes; a log-space product would only
        # approximate)
        np.testing.assert_array_equal(out, expected)
    else:
        np.testing.assert_allclose(out, expected, rtol=1e-4)


def test_allreduce_average_and_scales(mesh8):
    n = 8
    data = np.arange(n * 6, dtype=np.float32).reshape(n, 6)
    fn = C.build_allreduce(mesh8, WORLD_AXIS, ReduceOp.AVERAGE)
    out = np.asarray(fn(stacked(mesh8, data)))
    np.testing.assert_allclose(out, data.mean(axis=0), rtol=1e-6)

    fn2 = C.build_allreduce(mesh8, WORLD_AXIS, ReduceOp.SUM,
                            prescale_factor=0.5, postscale_factor=2.0)
    out2 = np.asarray(fn2(stacked(mesh8, data)))
    np.testing.assert_allclose(out2, data.sum(axis=0), rtol=1e-6)


def test_allgather(mesh8):
    n = 8
    data = np.random.RandomState(2).randn(n, 3, 4).astype(np.float32)
    fn = C.build_allgather(mesh8, WORLD_AXIS)
    out = np.asarray(fn(stacked(mesh8, data)))  # replicated: (n*3, 4)
    expected = data.reshape(n * 3, 4)
    np.testing.assert_array_equal(out, expected)


@pytest.mark.parametrize("root", [0, 3, 7])
def test_broadcast(mesh8, root):
    n = 8
    data = np.stack([np.full((5,), r, dtype=np.float32) for r in range(n)])
    fn = C.build_broadcast(mesh8, WORLD_AXIS, root)
    out = np.asarray(fn(stacked(mesh8, data)))  # replicated: (5,)
    np.testing.assert_array_equal(out, np.full((5,), root, np.float32))


def test_alltoall_equal(mesh8):
    n = 8
    # rank r sends value 100*r + dest
    data = np.zeros((n, n, 2), dtype=np.float32)
    for r in range(n):
        for d in range(n):
            data[r, d] = 100 * r + d
    fn = C.build_alltoall(mesh8, WORLD_AXIS)
    out = np.asarray(fn(stacked(mesh8, data)))
    for r in range(n):
        expected = np.stack([np.full((2,), 100 * s + r, np.float32) for s in range(n)])
        np.testing.assert_array_equal(out[r], expected)


def test_reducescatter(mesh8):
    n = 8
    data = np.random.RandomState(3).randn(n, 16, 3).astype(np.float32)
    fn = C.build_reducescatter(mesh8, WORLD_AXIS, ReduceOp.SUM)
    out = np.asarray(fn(stacked(mesh8, data)))  # (n, 2, 3)
    total = data.sum(axis=0)
    for r in range(n):
        np.testing.assert_allclose(out[r], total[r * 2:(r + 1) * 2], rtol=1e-5)


def test_barrier(mesh8):
    fn = C.build_barrier(mesh8, WORLD_AXIS)
    out = fn(jax.device_put(jnp.zeros((8,), jnp.int32),
                            NamedSharding(mesh8, P(WORLD_AXIS))))
    out.block_until_ready()


def test_bucketing():
    from horovod_tpu.core.engine import bucket_by_size
    ts = [jnp.zeros((1024,), jnp.float32),   # 4KB
          jnp.zeros((1024,), jnp.float32),
          jnp.zeros((1024,), jnp.int32),     # dtype change → new bucket
          jnp.zeros((2048,), jnp.int32)]
    buckets = bucket_by_size(ts, threshold_bytes=8 * 1024)
    assert buckets == [[0, 1], [2], [3]]
    buckets2 = bucket_by_size(ts, threshold_bytes=4 * 1024)
    assert buckets2 == [[0], [1], [2], [3]]
