"""Launcher unit tests (no processes spawned, mirrors reference
test/test_run.py:906 mocked-launcher tier)."""

import os

import pytest

from horovod_tpu.runner.hosts import (HostInfo, SlotInfo, get_host_assignments,
                                      parse_hosts, parse_host_files)
from horovod_tpu.runner.http_server import KVStoreServer, RendezvousServer
from horovod_tpu.runner.http_client import (put_data_into_kvstore,
                                            read_data_from_kvstore)
from horovod_tpu.runner import launch
from horovod_tpu.common import env as env_mod


class TestHosts:
    def test_parse_hosts(self):
        hosts = parse_hosts("a:2, b:4,c")
        assert hosts == [HostInfo("a", 2), HostInfo("b", 4), HostInfo("c", 1)]

    def test_parse_host_files(self, tmp_path):
        f = tmp_path / "hostfile"
        f.write_text("a slots=2\n# comment\nb:3\nc\n")
        assert parse_host_files(str(f)) == [
            HostInfo("a", 2), HostInfo("b", 3), HostInfo("c", 1)]

    def test_assignments_single_host(self):
        slots = get_host_assignments([HostInfo("localhost", 4)], 4)
        assert [s.rank for s in slots] == [0, 1, 2, 3]
        assert [s.local_rank for s in slots] == [0, 1, 2, 3]
        assert all(s.size == 4 and s.local_size == 4 for s in slots)
        assert all(s.cross_rank == 0 and s.cross_size == 1 for s in slots)

    def test_assignments_two_hosts(self):
        slots = get_host_assignments(
            [HostInfo("h1", 2), HostInfo("h2", 2)], 4)
        # host-major rank order, contiguous local ranks
        assert [(s.hostname, s.rank, s.local_rank) for s in slots] == [
            ("h1", 0, 0), ("h1", 1, 1), ("h2", 2, 0), ("h2", 3, 1)]
        # cross topology: rank0/rank2 share local_rank 0 across hosts
        assert slots[0].cross_rank == 0 and slots[2].cross_rank == 1
        assert all(s.cross_size == 2 for s in slots)

    def test_assignments_insufficient_slots(self):
        with pytest.raises(ValueError):
            get_host_assignments([HostInfo("h1", 1)], 2)

    def test_assignments_capped_max(self):
        slots = get_host_assignments(
            [HostInfo("h1", 4), HostInfo("h2", 4)], 2, max_np=3)
        assert len(slots) == 3
        assert [s.hostname for s in slots] == ["h1", "h1", "h1"]

    def test_slotinfo_roundtrip(self):
        s = SlotInfo("host-a", 3, 1, 2, 8, 4, 2)
        assert SlotInfo.from_response_string(s.to_response_string()) == s


class TestKVStore:
    def test_put_get(self):
        server = KVStoreServer(("127.0.0.1", 0))
        port = server.start()
        try:
            put_data_into_kvstore("127.0.0.1", port, "scope", "k", b"v1")
            assert read_data_from_kvstore("127.0.0.1", port, "scope", "k",
                                          timeout=5) == b"v1"
        finally:
            server.stop()

    def test_get_missing_times_out(self):
        server = KVStoreServer(("127.0.0.1", 0))
        port = server.start()
        try:
            with pytest.raises(TimeoutError):
                read_data_from_kvstore("127.0.0.1", port, "scope", "nope",
                                       timeout=0.5, poll_interval=0.1)
        finally:
            server.stop()

    def test_rendezvous_slot_lookup(self):
        slots = get_host_assignments(
            [HostInfo("h1", 2), HostInfo("h2", 2)], 4)
        server = RendezvousServer(("127.0.0.1", 0))
        port = server.start()
        try:
            server.init(slots, "10.0.0.1:1234")
            raw = read_data_from_kvstore("127.0.0.1", port,
                                         "rank_and_size", "h2:1", timeout=5)
            got = SlotInfo.from_response_string(raw.decode())
            assert (got.rank, got.local_rank, got.cross_rank) == (3, 1, 1)
            coord = read_data_from_kvstore("127.0.0.1", port,
                                           "coordinator", "addr", timeout=5)
            assert coord == b"10.0.0.1:1234"
        finally:
            server.stop()


class TestLaunchCLI:
    def test_worker_env(self):
        slot = SlotInfo("localhost", 1, 1, 0, 2, 2, 1)
        env = launch.make_worker_env(slot, "127.0.0.1:999", "127.0.0.1", 888,
                                     base_env={})
        assert env[env_mod.HOROVOD_RANK] == "1"
        assert env[env_mod.HOROVOD_SIZE] == "2"
        assert env[env_mod.HOROVOD_LOCAL_RANK] == "1"
        assert env[env_mod.HOROVOD_TPU_COORDINATOR] == "127.0.0.1:999"
        assert env[env_mod.HOROVOD_TPU_PROCESS_ID] == "1"
        assert env[env_mod.HOROVOD_GLOO_RENDEZVOUS_PORT] == "888"

    def test_slot_command_local_vs_ssh(self):
        local = SlotInfo("localhost", 0, 0, 0, 2, 1, 1)
        remote = SlotInfo("farhost", 1, 0, 1, 2, 1, 2)
        env = {"HOROVOD_RANK": "1", "SECRET_TOKEN": "x"}
        cmd_local = launch.slot_command(["python", "train.py"], env, local)
        assert cmd_local == "python train.py"
        cmd_remote = launch.slot_command(["python", "train.py"], env, remote)
        assert cmd_remote.startswith("ssh ")
        assert "farhost" in cmd_remote
        assert "HOROVOD_RANK=1" in cmd_remote
        # non-allowlisted env must not leak over ssh
        assert "SECRET_TOKEN" not in cmd_remote

    def test_parse_args_static(self):
        args = launch.parse_args(
            ["-np", "4", "-H", "a:2,b:2", "--timeline-filename", "/tmp/t.json",
             "--autotune", "--", "python", "train.py"])
        assert args.num_proc == 4 and args.hosts == "a:2,b:2"
        env = launch.env_from_args(args)
        assert env[env_mod.HOROVOD_TIMELINE] == "/tmp/t.json"
        assert env[env_mod.HOROVOD_AUTOTUNE] == "1"
        assert args.command == ["--", "python", "train.py"]

    def test_parse_args_config_file(self, tmp_path):
        cfg = tmp_path / "cfg.yaml"
        cfg.write_text("num-proc: 2\ntuning:\n  cycle-time-ms: 3.5\n")
        args = launch.parse_args(["--config-file", str(cfg), "python", "x.py"])
        assert args.num_proc == 2
        assert args.cycle_time_ms == 3.5
        env = launch.env_from_args(args)
        assert env[env_mod.HOROVOD_CYCLE_TIME] == "3.5"

    def test_main_requires_command(self, capsys):
        assert launch.main(["-np", "2"]) == 2

    def test_fusion_threshold_env(self):
        args = launch.parse_args(["-np", "1", "--fusion-threshold-mb", "32",
                                  "x"])
        env = launch.env_from_args(args)
        assert env[env_mod.HOROVOD_FUSION_THRESHOLD] == str(32 * 1024 * 1024)
