"""Configuration-knob registry lint (ISSUE 7 satellite): KNOB_SPECS shape
validation, the AST env-read scanner, undeclared/dead detection, and the
live-tree run.
"""

import os
import textwrap

import pytest

from horovod_tpu.analysis import knobcheck
from horovod_tpu.common.knobs import KNOB_SPECS

pytestmark = pytest.mark.lint

PKG_ROOT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "horovod_tpu")


class TestSpecValidation:
    def test_live_specs_clean(self):
        assert knobcheck.validate_specs(KNOB_SPECS) == []

    def test_bad_specs_flagged(self):
        errs = knobcheck.validate_specs({
            "not_upper": {"type": "bool", "default": "0", "help": "h"},
            "HOROVOD_TPU_NO_HELP": {"type": "int", "default": "1",
                                    "help": ""},
            "HOROVOD_TPU_BAD_TYPE": {"type": "enum", "default": "x",
                                     "help": "h"},
            "HOROVOD_TPU_NO_CHOICES": {"type": "choice", "default": "a",
                                       "help": "h"},
        })
        joined = "\n".join(errs)
        assert "not_upper: does not match" in joined
        assert "HOROVOD_TPU_NO_HELP: missing help" in joined
        assert "unknown knob type 'enum'" in joined
        assert "HOROVOD_TPU_NO_CHOICES: choice knobs must list" in joined


class TestScanner:
    def _scan(self, tmp_path, body, env_consts=""):
        pkg = tmp_path / "pkg"
        (pkg / "common").mkdir(parents=True)
        (pkg / "common" / "env.py").write_text(
            'HOROVOD_TPU_CONST_KNOB = "HOROVOD_TPU_CONST_KNOB"\n'
            + env_consts)
        (pkg / "mod.py").write_text(textwrap.dedent(body))
        return knobcheck.scan_env_reads(str(pkg))

    def test_literal_and_constant_and_helper_reads(self, tmp_path):
        sites = self._scan(tmp_path, """\
            import os
            from .common.env import HOROVOD_TPU_CONST_KNOB, _get_bool

            a = os.environ.get("HOROVOD_TPU_LIT_KNOB")
            b = os.environ["HOROVOD_TPU_SUB_KNOB"]
            c = os.getenv("HOROVOD_TPU_GETENV_KNOB", "1")
            d = _get_bool(HOROVOD_TPU_CONST_KNOB)
            os.environ["HOROVOD_TPU_WRITTEN"] = "1"   # store: not a read
            name = "dynamic"
            e = os.environ.get(name)                  # unresolvable: skip
            f = os.environ.get("PATH")                # non-HOROVOD: skip
            """)
        by_name = {site[2]: site[3] for site in sites}
        assert set(by_name) == {"HOROVOD_TPU_LIT_KNOB",
                                "HOROVOD_TPU_SUB_KNOB",
                                "HOROVOD_TPU_GETENV_KNOB",
                                "HOROVOD_TPU_CONST_KNOB"}
        # the reader form rides along for the choice-knob discipline
        assert by_name["HOROVOD_TPU_LIT_KNOB"] == "environ.get"
        assert by_name["HOROVOD_TPU_SUB_KNOB"] == "subscript"
        assert by_name["HOROVOD_TPU_GETENV_KNOB"] == "getenv"
        assert by_name["HOROVOD_TPU_CONST_KNOB"] == "_get_bool"

    def test_unparseable_file_is_reported_not_skipped(self, tmp_path):
        pkg = tmp_path / "pkg"
        (pkg / "common").mkdir(parents=True)
        (pkg / "common" / "env.py").write_text("X = 'X'\n")
        (pkg / "broken.py").write_text("def broken(:\n")
        errs = []
        knobcheck.scan_env_reads(str(pkg), errors=errs)
        assert len(errs) == 1
        assert "broken.py" in errs[0] and "could not parse" in errs[0]

    def test_undeclared_and_dead(self):
        specs = {
            "HOROVOD_TPU_USED": {"type": "bool", "default": "0",
                                 "help": "h"},
            "HOROVOD_TPU_DEAD": {"type": "bool", "default": "0",
                                 "help": "h"},
            "HOROVOD_TPU_EXPORTED": {"type": "int", "default": "1",
                                     "help": "h", "export": True},
        }
        sites = [("mod.py", 3, "HOROVOD_TPU_USED", "environ.get"),
                 ("mod.py", 9, "HOROVOD_TPU_UNDECLARED", "environ.get")]
        errs = knobcheck.validate_reads(specs, sites)
        joined = "\n".join(errs)
        assert "mod.py:9" in joined and "HOROVOD_TPU_UNDECLARED" in joined
        assert "HOROVOD_TPU_DEAD" in joined and "dead knob" in joined
        # export-only knobs are exempt from the dead check
        assert "HOROVOD_TPU_EXPORTED" not in joined
        assert len(errs) == 2


class TestDefaultsAndChoices:
    """ISSUE 11 satellite: defaults must match declared types/choices and
    choice knobs must go through the registry parser."""

    def test_live_defaults_clean(self):
        from horovod_tpu.common.knobs import KNOB_SPECS as specs
        assert knobcheck.validate_defaults(specs) == []

    def test_bad_defaults_flagged(self):
        errs = knobcheck.validate_defaults({
            "HOROVOD_TPU_BAD_CHOICE_DEFAULT": {
                "type": "choice", "default": "spiral",
                "choices": ("a", "b"), "help": "h"},
            "HOROVOD_TPU_BAD_INT": {"type": "int", "default": "many",
                                    "help": "h"},
            "HOROVOD_TPU_BAD_BOOL": {"type": "bool", "default": "si",
                                     "help": "h"},
            "HOROVOD_TPU_OK_DISPLAY": {
                "type": "int", "default": "100 (10 when elastic)",
                "help": "h"},
            "HOROVOD_TPU_OK_DERIVED": {"type": "int", "default": "derived",
                                       "help": "h"},
        })
        joined = "\n".join(errs)
        assert "'spiral' is not one of its own choices" in joined
        assert "int default 'many' does not parse" in joined
        assert "bool default 'si'" in joined
        assert "OK_DISPLAY" not in joined and "OK_DERIVED" not in joined
        assert len(errs) == 3

    def test_raw_choice_read_flagged(self):
        specs = {"HOROVOD_TPU_MODE": {"type": "choice", "default": "a",
                                      "choices": ("a", "b"), "help": "h"}}
        sites = [("mod.py", 5, "HOROVOD_TPU_MODE", "environ.get"),
                 ("mod.py", 9, "HOROVOD_TPU_MODE", "_get_choice")]
        errs = knobcheck.validate_choice_reads(specs, sites)
        assert len(errs) == 1
        assert "mod.py:5" in errs[0] and "environ.get" in errs[0]

    def test_live_tree_has_no_raw_choice_reads(self):
        # regression for the fixed drift: HOROVOD_SPLASH was read raw in
        # two places with two different defaults and a wider accepted
        # token set than the registry declared
        from horovod_tpu.common.knobs import KNOB_SPECS as specs
        sites = knobcheck.scan_env_reads(PKG_ROOT)
        assert knobcheck.validate_choice_reads(specs, sites) == []

    def test_splash_mode_parses_through_registry(self, monkeypatch, caplog):
        import logging
        from horovod_tpu.parallel.flash_attention import _splash_mode
        monkeypatch.delenv("HOROVOD_SPLASH", raising=False)
        assert _splash_mode() == "1"
        monkeypatch.setenv("HOROVOD_SPLASH", "force")
        assert _splash_mode() == "force"
        # every historically-working token keeps its direction: the
        # boolean aliases are declared choices, so a deliberate
        # HOROVOD_SPLASH=off still disables — no fail-safe inversion
        for tok in ("0", "off", "false", "no"):
            monkeypatch.setenv("HOROVOD_SPLASH", tok)
            assert _splash_mode() == "0", tok
        for tok in ("1", "on", "true", "yes"):
            monkeypatch.setenv("HOROVOD_SPLASH", tok)
            assert _splash_mode() == "1", tok
        # set-but-empty follows the framework-wide convention (every
        # registry parser treats empty as unset): default, enabled
        monkeypatch.setenv("HOROVOD_SPLASH", "")
        assert _splash_mode() == "1"
        # unknown tokens warn loudly and take the default — the
        # _get_choice discipline, not a silent ad-hoc fallback
        monkeypatch.setenv("HOROVOD_SPLASH", "definitely-not-a-mode")
        with caplog.at_level(logging.WARNING, logger="horovod_tpu"):
            assert _splash_mode() == "1"
        assert any("HOROVOD_SPLASH" in r.message for r in caplog.records)


class TestLiveTree:
    def test_every_env_read_is_declared_and_alive(self):
        errors, stats = knobcheck.run(PKG_ROOT)
        assert errors == [], "\n".join(errors)
        # the repo has ~75 knobs; a scan suddenly seeing far fewer means
        # the scanner regressed, not that the env plane shrank
        assert stats["distinct_read"] >= 70
        assert stats["declared"] >= stats["distinct_read"]

    def test_docs_section_renders_every_knob(self):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "gen_api_docs", os.path.join(
                os.path.dirname(PKG_ROOT), "tools", "gen_api_docs.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        text = "\n".join(mod.knob_section())
        for name in KNOB_SPECS:
            assert f"`{name}`" in text, f"{name} missing from docs section"
