"""Configuration-knob registry lint (ISSUE 7 satellite): KNOB_SPECS shape
validation, the AST env-read scanner, undeclared/dead detection, and the
live-tree run.
"""

import os
import textwrap

import pytest

from horovod_tpu.analysis import knobcheck
from horovod_tpu.common.knobs import KNOB_SPECS

pytestmark = pytest.mark.lint

PKG_ROOT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "horovod_tpu")


class TestSpecValidation:
    def test_live_specs_clean(self):
        assert knobcheck.validate_specs(KNOB_SPECS) == []

    def test_bad_specs_flagged(self):
        errs = knobcheck.validate_specs({
            "not_upper": {"type": "bool", "default": "0", "help": "h"},
            "HOROVOD_TPU_NO_HELP": {"type": "int", "default": "1",
                                    "help": ""},
            "HOROVOD_TPU_BAD_TYPE": {"type": "enum", "default": "x",
                                     "help": "h"},
            "HOROVOD_TPU_NO_CHOICES": {"type": "choice", "default": "a",
                                       "help": "h"},
        })
        joined = "\n".join(errs)
        assert "not_upper: does not match" in joined
        assert "HOROVOD_TPU_NO_HELP: missing help" in joined
        assert "unknown knob type 'enum'" in joined
        assert "HOROVOD_TPU_NO_CHOICES: choice knobs must list" in joined


class TestScanner:
    def _scan(self, tmp_path, body, env_consts=""):
        pkg = tmp_path / "pkg"
        (pkg / "common").mkdir(parents=True)
        (pkg / "common" / "env.py").write_text(
            'HOROVOD_TPU_CONST_KNOB = "HOROVOD_TPU_CONST_KNOB"\n'
            + env_consts)
        (pkg / "mod.py").write_text(textwrap.dedent(body))
        return knobcheck.scan_env_reads(str(pkg))

    def test_literal_and_constant_and_helper_reads(self, tmp_path):
        sites = self._scan(tmp_path, """\
            import os
            from .common.env import HOROVOD_TPU_CONST_KNOB, _get_bool

            a = os.environ.get("HOROVOD_TPU_LIT_KNOB")
            b = os.environ["HOROVOD_TPU_SUB_KNOB"]
            c = os.getenv("HOROVOD_TPU_GETENV_KNOB", "1")
            d = _get_bool(HOROVOD_TPU_CONST_KNOB)
            os.environ["HOROVOD_TPU_WRITTEN"] = "1"   # store: not a read
            name = "dynamic"
            e = os.environ.get(name)                  # unresolvable: skip
            f = os.environ.get("PATH")                # non-HOROVOD: skip
            """)
        names = {n for _, _, n in sites}
        assert names == {"HOROVOD_TPU_LIT_KNOB", "HOROVOD_TPU_SUB_KNOB",
                         "HOROVOD_TPU_GETENV_KNOB",
                         "HOROVOD_TPU_CONST_KNOB"}

    def test_unparseable_file_is_reported_not_skipped(self, tmp_path):
        pkg = tmp_path / "pkg"
        (pkg / "common").mkdir(parents=True)
        (pkg / "common" / "env.py").write_text("X = 'X'\n")
        (pkg / "broken.py").write_text("def broken(:\n")
        errs = []
        knobcheck.scan_env_reads(str(pkg), errors=errs)
        assert len(errs) == 1
        assert "broken.py" in errs[0] and "could not parse" in errs[0]

    def test_undeclared_and_dead(self):
        specs = {
            "HOROVOD_TPU_USED": {"type": "bool", "default": "0",
                                 "help": "h"},
            "HOROVOD_TPU_DEAD": {"type": "bool", "default": "0",
                                 "help": "h"},
            "HOROVOD_TPU_EXPORTED": {"type": "int", "default": "1",
                                     "help": "h", "export": True},
        }
        sites = [("mod.py", 3, "HOROVOD_TPU_USED"),
                 ("mod.py", 9, "HOROVOD_TPU_UNDECLARED")]
        errs = knobcheck.validate_reads(specs, sites)
        joined = "\n".join(errs)
        assert "mod.py:9" in joined and "HOROVOD_TPU_UNDECLARED" in joined
        assert "HOROVOD_TPU_DEAD" in joined and "dead knob" in joined
        # export-only knobs are exempt from the dead check
        assert "HOROVOD_TPU_EXPORTED" not in joined
        assert len(errs) == 2


class TestLiveTree:
    def test_every_env_read_is_declared_and_alive(self):
        errors, stats = knobcheck.run(PKG_ROOT)
        assert errors == [], "\n".join(errors)
        # the repo has ~75 knobs; a scan suddenly seeing far fewer means
        # the scanner regressed, not that the env plane shrank
        assert stats["distinct_read"] >= 70
        assert stats["declared"] >= stats["distinct_read"]

    def test_docs_section_renders_every_knob(self):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "gen_api_docs", os.path.join(
                os.path.dirname(PKG_ROOT), "tools", "gen_api_docs.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        text = "\n".join(mod.knob_section())
        for name in KNOB_SPECS:
            assert f"`{name}`" in text, f"{name} missing from docs section"
