"""Adasum numerical tests vs the NumPy VHDD reference (mirrors the reference's
test/test_adasum_pytorch.py:1-210 strategy: compare the distributed result
against a host-side formula)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.ops.adasum import build_adasum, adasum_reference, adasum_combine
from horovod_tpu.parallel.mesh import WORLD_AXIS


def stacked(mesh, per_rank):
    return jax.device_put(jnp.asarray(per_rank), NamedSharding(mesh, P(WORLD_AXIS)))


def test_adasum_combine_orthogonal():
    # Orthogonal vectors: dot=0 → plain sum.
    a = jnp.array([1.0, 0.0, 0.0])
    b = jnp.array([0.0, 1.0, 0.0])
    out = np.asarray(adasum_combine(a, b))
    np.testing.assert_allclose(out, [1.0, 1.0, 0.0], rtol=1e-6)


def test_adasum_combine_parallel():
    # Identical vectors: dot=|a|^2=|b|^2 → coefficients 1/2 → average·2/2 = a.
    a = jnp.array([2.0, -1.0, 3.0])
    out = np.asarray(adasum_combine(a, a))
    np.testing.assert_allclose(out, np.asarray(a), rtol=1e-6)


@pytest.mark.parametrize("shape", [(32,), (7, 5)])
def test_adasum_vhdd_matches_reference(mesh8, shape):
    n = 8
    rng = np.random.RandomState(42)
    data = rng.randn(n, *shape).astype(np.float32)
    fn = build_adasum(mesh8, WORLD_AXIS)
    out = np.asarray(fn(stacked(mesh8, data)))  # replicated: (*shape)
    expected = adasum_reference([data[r] for r in range(n)]).reshape(shape)
    np.testing.assert_allclose(out, expected, rtol=1e-4, atol=1e-5)


def test_adasum_requires_power_of_2(mesh8):
    from horovod_tpu.ops.adasum import adasum_p
    with pytest.raises(ValueError):
        adasum_p(jnp.zeros((4,)), WORLD_AXIS, 6)


# ---------------------------------------------------------------------------
# Delta-model Adasum (reference torch/optimizer.py:196-364): local optimizer
# step first, Adasum-reduce the parameter DELTA. Same test strategy as above
# — compare the distributed result against the NumPy VHDD formula applied to
# host-computed per-rank deltas.
# ---------------------------------------------------------------------------


def _per_rank_updates(grads, params, n, steps_state=None):
    """Host-side reference: each rank's Adam update on its local grads."""
    import optax
    inner = optax.adam(1e-2)
    outs = []
    for r in range(n):
        st = inner.init(params)
        u, _ = inner.update(jax.tree_util.tree_map(lambda g: g[r], grads),
                            st, params)
        outs.append(u)
    return outs


def test_delta_adasum_matches_numpy_reference(mesh8):
    """delta-Adasum == params + VHDD(per-rank Adam updates), with the
    per-rank updates computed from LOCAL grads (the property that makes
    the delta form scale-invariant under adaptive optimizers)."""
    import optax
    from jax import shard_map
    from horovod_tpu.optimizer import distributed_delta_adasum

    n = 8
    rng = np.random.RandomState(7)
    params = {"w": jnp.asarray(rng.randn(4, 3).astype(np.float32)),
              "b": jnp.asarray(rng.randn(3).astype(np.float32))}
    grads = {"w": rng.randn(n, 4, 3).astype(np.float32),
             "b": rng.randn(n, 3).astype(np.float32)}

    opt = distributed_delta_adasum(optax.adam(1e-2), WORLD_AXIS, n)
    state = opt.init(params)

    def body(g, params):
        g = jax.tree_util.tree_map(lambda a: a[0], g)  # drop the block dim
        u, _ = opt.update(g, state, params)
        return optax.apply_updates(params, u)

    fn = jax.jit(shard_map(
        body, mesh=mesh8, in_specs=(P(WORLD_AXIS), P()), out_specs=P(),
        check_vma=False))
    out = fn({"w": stacked(mesh8, grads["w"]),
              "b": stacked(mesh8, grads["b"])}, params)

    ref_updates = _per_rank_updates(grads, params, n)
    for k in ("w", "b"):
        expect = np.asarray(params[k]) + adasum_reference(
            [np.asarray(u[k]) for u in ref_updates]).reshape(params[k].shape)
        np.testing.assert_allclose(np.asarray(out[k]), expect,
                                   rtol=1e-4, atol=1e-5)


def test_delta_adasum_differs_from_grad_adasum_under_adam(mesh8):
    """The reason the delta form exists: under Adam the local preconditioner
    runs BEFORE the Adasum mixing, so delta-Adasum and grad-Adasum give
    different parameters (they coincide only for plain SGD, where the
    update is a linear function of the gradient)."""
    import optax
    from jax import shard_map
    from horovod_tpu.optimizer import (allreduce_gradients,
                                       distributed_delta_adasum)
    from horovod_tpu.common.reduce_ops import Adasum

    n = 8
    rng = np.random.RandomState(9)
    params = {"w": jnp.asarray(rng.randn(6).astype(np.float32))}
    grads = {"w": (rng.randn(n, 6) * rng.uniform(0.1, 10, size=(n, 1)))
             .astype(np.float32)}

    delta_opt = distributed_delta_adasum(optax.adam(1e-2), WORLD_AXIS, n)
    dstate = delta_opt.init(params)

    def body_delta(g, params):
        g = jax.tree_util.tree_map(lambda a: a[0], g)
        u, _ = delta_opt.update(g, dstate, params)
        return optax.apply_updates(params, u)

    inner = optax.adam(1e-2)
    gstate = inner.init(params)

    def body_grad(g, params):
        g = jax.tree_util.tree_map(lambda a: a[0], g)
        rg = allreduce_gradients(g, WORLD_AXIS, op=Adasum, axis_size=n)
        u, _ = inner.update(rg, gstate, params)
        return optax.apply_updates(params, u)

    sharded = {"w": stacked(mesh8, grads["w"])}
    out_d = jax.jit(shard_map(body_delta, mesh=mesh8,
                              in_specs=(P(WORLD_AXIS), P()), out_specs=P(),
                              check_vma=False))(sharded, params)
    out_g = jax.jit(shard_map(body_grad, mesh=mesh8,
                              in_specs=(P(WORLD_AXIS), P()), out_specs=P(),
                              check_vma=False))(sharded, params)
    # both moved the params...
    assert not np.allclose(np.asarray(out_d["w"]), np.asarray(params["w"]))
    assert not np.allclose(np.asarray(out_g["w"]), np.asarray(params["w"]))
    # ...to different points
    assert not np.allclose(np.asarray(out_d["w"]), np.asarray(out_g["w"]),
                           rtol=1e-3)


def test_delta_adasum_eager_size1_is_local_step():
    """Eager plumbing at world size 1: Adasum of one rank is the identity,
    so update_and_apply must equal the plain inner step (and chain with no
    host block)."""
    import optax
    import horovod_tpu as hvd

    hvd.init()
    rng = np.random.RandomState(11)
    params = {"w": jnp.asarray(rng.randn(5, 2).astype(np.float32))}
    grads = {"w": jnp.asarray(rng.randn(5, 2).astype(np.float32))}

    inner = optax.adam(1e-2)
    ref_state = inner.init(params)
    u, _ = inner.update(grads, ref_state, params)
    expect = optax.apply_updates(params, u)

    opt = hvd.DistributedDeltaAdasumOptimizer(optax.adam(1e-2))
    st = opt.init(params)
    out, _ = opt.update_and_apply(grads, st, params)
    np.testing.assert_allclose(np.asarray(out["w"]),
                               np.asarray(expect["w"]), rtol=1e-6)
