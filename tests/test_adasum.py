"""Adasum numerical tests vs the NumPy VHDD reference (mirrors the reference's
test/test_adasum_pytorch.py:1-210 strategy: compare the distributed result
against a host-side formula)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.ops.adasum import build_adasum, adasum_reference, adasum_combine
from horovod_tpu.parallel.mesh import WORLD_AXIS


def stacked(mesh, per_rank):
    return jax.device_put(jnp.asarray(per_rank), NamedSharding(mesh, P(WORLD_AXIS)))


def test_adasum_combine_orthogonal():
    # Orthogonal vectors: dot=0 → plain sum.
    a = jnp.array([1.0, 0.0, 0.0])
    b = jnp.array([0.0, 1.0, 0.0])
    out = np.asarray(adasum_combine(a, b))
    np.testing.assert_allclose(out, [1.0, 1.0, 0.0], rtol=1e-6)


def test_adasum_combine_parallel():
    # Identical vectors: dot=|a|^2=|b|^2 → coefficients 1/2 → average·2/2 = a.
    a = jnp.array([2.0, -1.0, 3.0])
    out = np.asarray(adasum_combine(a, a))
    np.testing.assert_allclose(out, np.asarray(a), rtol=1e-6)


@pytest.mark.parametrize("shape", [(32,), (7, 5)])
def test_adasum_vhdd_matches_reference(mesh8, shape):
    n = 8
    rng = np.random.RandomState(42)
    data = rng.randn(n, *shape).astype(np.float32)
    fn = build_adasum(mesh8, WORLD_AXIS)
    out = np.asarray(fn(stacked(mesh8, data)))  # replicated: (*shape)
    expected = adasum_reference([data[r] for r in range(n)]).reshape(shape)
    np.testing.assert_allclose(out, expected, rtol=1e-4, atol=1e-5)


def test_adasum_requires_power_of_2(mesh8):
    from horovod_tpu.ops.adasum import adasum_p
    with pytest.raises(ValueError):
        adasum_p(jnp.zeros((4,)), WORLD_AXIS, 6)
