"""Measured performance model + persistent fleet autotune (ISSUE 14).

Covers the three tentpole layers on the CPU test world:

- **calibration** — the α–β fit, the derived ring/tree and
  flat/hierarchical crossovers, the MeasuredTopology overlay, and the
  probe-disabled fallback to nominal tables;
- **joint search** — string-valued categoricals (the PR 10
  boolean-over-string encoding retired), the tree-threshold numeric dim,
  and calibrated-prediction seeding;
- **persistence** — tuning-record round trip keyed by (model signature,
  topology digest), stale-digest rejection, nearest-key priors for
  elastic N→M resizes, and the engine-level warm start that reaches the
  stored knob vector in <= 1 autotune cycle.

The real multi-rank probe determinism case lives in
tests/test_multiprocess.py (np=2, probing on); the in-process probe
smoke here is ``perf``-marked per the tier-1 convention.
"""

import json
import math
import os
import sys
import time

import numpy as np
import pytest

from horovod_tpu.autotune.calibration import (
    A2A_CLASS_FLAT, A2A_CLASS_HIER, HIER_THRESHOLD_MAX,
    TREE_THRESHOLD_MAX, TREE_THRESHOLD_MIN,
    derived_alltoall_threshold_bytes, derived_hier_threshold_bytes,
    derived_thresholds, derived_tree_threshold_bytes, fit_alpha_beta,
    fit_measured_topology)
from horovod_tpu.autotune.parameter_manager import ParameterManager
from horovod_tpu.autotune.persistence import (TuningStore, kv_key,
                                              record_filename)
from horovod_tpu.parallel.mesh import (MeasuredTopology, Topology,
                                       measured_topology)

MB = 1024 * 1024
TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")


# ---------------------------------------------------------------------------
# calibration: α–β fit + derived crossovers
# ---------------------------------------------------------------------------

class TestCalibrationFit:
    def test_fit_recovers_known_model(self):
        alpha, beta = 2e-4, 5e9
        sizes = [64e3, 512e3, 4e6]
        times = [alpha + s / beta for s in sizes]
        a, b = fit_alpha_beta(sizes, times)
        assert a == pytest.approx(alpha, rel=1e-6)
        assert b == pytest.approx(beta, rel=1e-6)

    def test_fit_degenerate_slope_degrades_to_latency_only(self):
        # pure noise where bigger messages measured FASTER: the bandwidth
        # term must drop out (inf), never go negative
        a, b = fit_alpha_beta([1e5, 1e6], [2e-3, 1e-3])
        assert b == float("inf")
        assert a >= 0.0

    def test_tree_threshold_grows_with_latency(self):
        lo = derived_tree_threshold_bytes(1e-5, 1e9, 8)
        hi = derived_tree_threshold_bytes(1e-3, 1e9, 8)
        assert hi > lo
        assert TREE_THRESHOLD_MIN <= lo <= hi <= TREE_THRESHOLD_MAX

    def test_tree_threshold_floor_below_4_ranks(self):
        # n=2: tree and flat are the same exchange, auto never offers it
        assert derived_tree_threshold_bytes(1e-3, 1e9, 2) == \
            TREE_THRESHOLD_MIN

    def test_hier_threshold_zero_when_ladder_never_slower(self):
        assert derived_hier_threshold_bytes((2e-4, 1e9), (1e-4, 4e9)) == 0

    def test_hier_threshold_caps_when_no_bandwidth_win(self):
        # ladder costs extra launches and measured NO bandwidth gain:
        # selection should keep flat for every realistic bucket
        assert derived_hier_threshold_bytes((1e-4, 1e9), (4e-4, 1e9)) == \
            HIER_THRESHOLD_MAX

    def test_hier_threshold_crossover_math(self):
        flat, hier = (1e-4, 1e9), (3e-4, 4e9)
        s = derived_hier_threshold_bytes(flat, hier)
        # at the crossover both cost models agree
        t_flat = flat[0] + s / flat[1]
        t_hier = hier[0] + s / hier[1]
        assert t_flat == pytest.approx(t_hier, rel=1e-3)


class TestMeasuredTopology:
    def _base(self, size=8, local=4):
        return Topology(size=size, local_size=local, platform="cpu")

    def test_overlay_preserves_shape_and_digest(self):
        base = self._base()
        m = measured_topology(base, 6.5, 0.8, 15.0,
                              {"flat": (1e-4, 1e9),
                               "hierarchical": (3e-4, 4e9)})
        assert isinstance(m, MeasuredTopology)
        assert (m.size, m.local_size, m.num_slices) == (8, 4, 2)
        assert m.hierarchical_ok
        assert m.calibrated and not base.calibrated
        # calibration must never fork the persistence key
        assert m.digest() == base.digest()
        assert m.ici_gbps == 6.5 and m.dcn_gbps == 0.8
        assert m.nominal_ici_gbps == base.ici_gbps
        assert m.fitted("flat") == (1e-4, 1e9)
        assert m.fitted("tree") is None
        d = m.describe()
        assert d["calibrated"] and "link_model" in d

    def test_digest_tracks_shape_not_measurement(self):
        a = self._base(8, 4)
        assert a.digest() != self._base(8, 2).digest()
        assert a.digest() != self._base(4, 4).digest()
        assert a.digest() != Topology(size=8, local_size=4,
                                      platform="tpu").digest()
        # bandwidths and detection source do not key records
        b = Topology(size=8, local_size=4, platform="cpu",
                     source="override", ici_gbps=99.0, dcn_gbps=9.0)
        assert a.digest() == b.digest()

    def test_fit_measured_topology_flat_world(self):
        base = Topology(size=4, local_size=1, platform="cpu")
        beta = 2e9
        agreed = {"flat": [1e-4 + s / beta
                           for s in (64e3, 512e3, 4e6)]}
        m = fit_measured_topology(base, agreed, bands=(64e3, 512e3, 4e6))
        # flat world: the ring measures ICI; busbw convention 2(n-1)/n
        assert m.ici_gbps == pytest.approx(
            2 * 3 / 4 * beta / 1e9, rel=1e-3)
        assert m.launch_latency_us > 0
        tree_thr, hier_thr = derived_thresholds(m)
        assert TREE_THRESHOLD_MIN <= tree_thr <= TREE_THRESHOLD_MAX
        assert hier_thr == 0     # ladder unprobed -> nominal behavior

    def test_fit_measured_topology_multislice(self):
        base = Topology(size=8, local_size=4, platform="cpu")
        agreed = {
            "flat": [1e-4 + s / 1e9 for s in (64e3, 512e3, 4e6)],
            "hierarchical": [3e-4 + s / 3e9 for s in (64e3, 512e3, 4e6)],
        }
        m = fit_measured_topology(base, agreed, bands=(64e3, 512e3, 4e6))
        assert m.is_multislice and m.calibrated
        # the flat ring is DCN-paced on multislice fabrics
        assert m.dcn_gbps == pytest.approx(2 * 7 / 8 * 1e9 / 1e9,
                                           rel=1e-3)
        tree_thr, hier_thr = derived_thresholds(m)
        # ladder costs extra α but wins bandwidth: finite crossover
        assert 0 < hier_thr < HIER_THRESHOLD_MAX

    def test_choose_algorithm_respects_hier_threshold(self):
        from horovod_tpu.ops import collectives as C
        topo = Topology(size=6, local_size=3, platform="cpu")
        below = C.choose_algorithm("allreduce", 1 * MB, topo,
                                   tree_threshold_bytes=0,
                                   hier_threshold_bytes=2 * MB)
        above = C.choose_algorithm("allreduce", 4 * MB, topo,
                                   tree_threshold_bytes=0,
                                   hier_threshold_bytes=2 * MB)
        assert below == C.ALGO_FLAT
        assert above == C.ALGO_HIERARCHICAL
        # default 0 keeps the nominal always-hierarchical behavior
        assert C.choose_algorithm("allreduce", 1 * MB, topo,
                                  tree_threshold_bytes=0) == \
            C.ALGO_HIERARCHICAL


class TestAlltoallCalibrationBand:
    """ISSUE 17: the alltoall band fits its own α–β rows and derives a
    measured flat-vs-hierarchical dispatch crossover."""

    BANDS = (64e3, 512e3, 4e6)

    def test_a2a_rows_fit_and_derive_finite_crossover(self):
        base = Topology(size=8, local_size=4, platform="cpu")
        agreed = {
            "flat": [1e-4 + s / 1e9 for s in self.BANDS],
            "hierarchical": [3e-4 + s / 3e9 for s in self.BANDS],
            A2A_CLASS_FLAT: [1e-4 + s / 2e9 for s in self.BANDS],
            A2A_CLASS_HIER: [4e-4 + s / 8e9 for s in self.BANDS],
        }
        m = fit_measured_topology(base, agreed, bands=self.BANDS)
        # the extra classes ride the same fit: rows present and sane
        a_f, b_f = m.fitted(A2A_CLASS_FLAT)
        a_h, b_h = m.fitted(A2A_CLASS_HIER)
        assert a_f == pytest.approx(1e-4, rel=1e-3)
        assert b_f == pytest.approx(2e9, rel=1e-3)
        assert a_h == pytest.approx(4e-4, rel=1e-3)
        assert b_h == pytest.approx(8e9, rel=1e-3)
        thr = derived_alltoall_threshold_bytes(m)
        assert thr is not None and 0 < thr < HIER_THRESHOLD_MAX
        # crossover: flat and hier cost curves meet exactly there
        assert a_f + thr / b_f == pytest.approx(a_h + thr / b_h,
                                                rel=1e-3)
        # the alltoall band never perturbs the allreduce crossovers
        tree_thr, hier_thr = derived_thresholds(m)
        assert 0 < hier_thr < HIER_THRESHOLD_MAX

    def test_unprobed_band_returns_none(self):
        base = Topology(size=8, local_size=4, platform="cpu")
        agreed = {
            "flat": [1e-4 + s / 1e9 for s in self.BANDS],
            A2A_CLASS_FLAT: [1e-4 + s / 2e9 for s in self.BANDS],
        }
        m = fit_measured_topology(base, agreed, bands=self.BANDS)
        # hierarchical leg unprobed (single slice, or probe vetoed):
        # no measured crossover — the nominal default stays in force
        assert derived_alltoall_threshold_bytes(m) is None
        assert derived_alltoall_threshold_bytes(
            fit_measured_topology(base,
                                  {"flat": agreed["flat"]},
                                  bands=self.BANDS)) is None

    def test_busbw_convention_alltoall(self):
        from horovod_tpu.autotune.calibration import _busbw_factor
        assert _busbw_factor("alltoall", 8) == pytest.approx(7 / 8)
        assert _busbw_factor("allgather", 8) == pytest.approx(7 / 8)
        assert _busbw_factor("allreduce", 8) == pytest.approx(2 * 7 / 8)


# ---------------------------------------------------------------------------
# joint search: string categoricals, tree-threshold dim, seeding
# ---------------------------------------------------------------------------

def _pm(**kw):
    kw.setdefault("warmup_samples", 0)
    kw.setdefault("steps_per_sample", 1)
    kw.setdefault("max_samples", 4)
    return ParameterManager(**kw)


def _drive_to_convergence(pm, nbytes=4 * MB, limit=200):
    for _ in range(limit):
        if not pm.active:
            return
        if pm._step_start is not None:
            pm._step_start -= 0.01
        pm.step_mark(nbytes)
    raise AssertionError("tuner did not converge")


class TestStringCategoricals:
    CHOICES = ("off", "interleave", "staged")

    def test_string_choices_decode_evenly(self):
        pm = _pm(categorical=[("overlap_pipeline", self.CHOICES)],
                 categorical_initial={"overlap_pipeline": "staged"})
        assert pm.tunes("overlap_pipeline")
        assert pm.categorical_choices("overlap_pipeline") == self.CHOICES
        assert pm.categorical_value("overlap_pipeline") == "staged"
        i = pm._cat_offset
        for u, want in ((0.0, "off"), (0.4, "interleave"),
                        (0.99, "staged"), (1.0, "staged")):
            pm._current[i] = u
            assert pm.categorical_value("overlap_pipeline") == want

    def test_boolean_backcompat(self):
        pm = _pm(categorical=["step_replay"],
                 categorical_initial={"step_replay": False})
        assert pm.categorical_value("step_replay") is False
        pm._current[pm._cat_offset] = 0.9
        assert pm.categorical_value("step_replay") is True

    def test_unknown_initial_lands_on_first_choice(self):
        pm = _pm(categorical=[("collective_algo", ("auto", "flat"))],
                 categorical_initial={"collective_algo": "bogus"})
        assert pm.categorical_value("collective_algo") == "auto"

    def test_encode_round_trips_choices(self):
        pm = _pm(categorical=[("collective_algo",
                               ("auto", "flat", "tree", "hierarchical"))],
                 tune_tree_threshold=True)
        for choice in ("auto", "flat", "tree", "hierarchical"):
            pm._current = pm.encode(
                fusion_threshold_bytes=8 * MB,
                tree_threshold_bytes=512 * 1024,
                categorical_values={"collective_algo": choice})
            assert pm.categorical_value("collective_algo") == choice
            assert pm.fusion_threshold_bytes == 8 * MB
            assert pm.tree_threshold_bytes == 512 * 1024

    def test_fewer_than_two_choices_rejected(self):
        with pytest.raises(ValueError):
            _pm(categorical=[("bad", ("only",))])

    def test_log_columns_carry_string_values(self, tmp_path):
        log = str(tmp_path / "t.csv")
        pm = _pm(categorical=[("collective_algo", ("auto", "flat")),
                              "step_replay"],
                 categorical_initial={"collective_algo": "auto",
                                      "step_replay": True},
                 log_path=log, max_samples=3)
        _drive_to_convergence(pm)
        lines = open(log).read().strip().splitlines()
        assert lines[0].endswith(
            "collective_algo,step_replay,score_bytes_per_sec")
        # value columns: a string for the choice knob, 0/1 for the bool
        row = lines[1].split(",")
        assert row[-3] in ("auto", "flat")
        assert row[-2] in ("0", "1")

    def test_knob_values_snapshot(self):
        pm = _pm(categorical=[("compression", ("none", "int8"))],
                 categorical_initial={"compression": "int8"},
                 tune_tree_threshold=True,
                 initial_tree_threshold=128 * 1024)
        vals = pm.knob_values()
        assert vals["compression"] == "int8"
        assert vals["tree_threshold_bytes"] == 128 * 1024
        assert "fusion_threshold_bytes" in vals


class TestTreeThresholdDimension:
    def test_dim_present_and_bounded(self):
        pm = _pm(tune_tree_threshold=True, initial_tree_threshold=1)
        lo, hi = ParameterManager.TREE_THRESHOLD_BOUNDS
        assert pm.tunes_tree_threshold
        assert pm.tree_threshold_bytes == lo       # clamped up
        assert len(pm._bounds) == 3
        assert pm.space()["numeric"][-1] == "tree_threshold_bytes"

    def test_absent_by_default(self):
        pm = _pm()
        assert not pm.tunes_tree_threshold
        with pytest.raises(ValueError):
            pm.tree_threshold_bytes


class TestMixedSpaceOptimizer:
    def test_suggestions_land_on_slot_centers(self):
        from horovod_tpu.autotune.bayesian_optimization import \
            BayesianOptimizer
        opt = BayesianOptimizer([(0.0, 10.0), (0.0, 1.0), (0.0, 1.0)],
                                seed=3,
                                categorical_slots={1: 2, 2: 3})
        centers2 = {(i + 0.5) / 2 for i in range(2)}
        centers3 = {(i + 0.5) / 3 for i in range(3)}
        for i in range(8):
            x = opt.suggest()
            assert float(x[1]) in centers2, x
            assert float(x[2]) in centers3, x
            opt.register(x, float(-(x[0] - 7.0) ** 2))
        # numeric dim still continuous (not snapped)
        assert 0.0 <= x[0] <= 10.0

    def test_pm_wires_slots_for_every_categorical(self):
        pm = _pm(categorical=["step_replay",
                              ("collective_algo", ("auto", "flat",
                                                   "tree"))],
                 tune_tree_threshold=True)
        assert pm._opt.categorical_slots == {3: 2, 4: 3}


class TestSeedSuggestions:
    def test_seeds_explored_before_random(self):
        pm = _pm(max_samples=10)
        seed1 = pm.encode(fusion_threshold_bytes=2 * MB)
        seed2 = pm.encode(fusion_threshold_bytes=128 * MB)
        pm._seed_suggestions.extend([seed1, seed2])
        # first sample moves to seed1, second to seed2
        pm._step_start = time.perf_counter() - 0.01
        pm.step_mark(4 * MB)
        assert pm.fusion_threshold_bytes == 2 * MB
        pm._step_start -= 0.01
        pm.step_mark(4 * MB)
        assert pm.fusion_threshold_bytes == 128 * MB


# ---------------------------------------------------------------------------
# persistence: record round trip, stale rejection, nearest key
# ---------------------------------------------------------------------------

def _converged_store(tmp_path, topo, model_sig="m" * 64, **pm_kw):
    pm = _pm(categorical=[("collective_algo", ("auto", "flat"))],
             tune_tree_threshold=True, **pm_kw)
    store = TuningStore(str(tmp_path), topo, rank=0)
    pm.attach_persistence(store)
    pm._model_sig = model_sig
    _drive_to_convergence(pm)
    return pm, store


class TestTuningStore:
    TOPO = Topology(size=2, local_size=1, platform="cpu")

    def test_round_trip_exact(self, tmp_path):
        pm, store = _converged_store(tmp_path, self.TOPO)
        path = tmp_path / record_filename("m" * 64, self.TOPO.digest())
        assert path.exists()
        rec = json.loads(path.read_text())
        assert rec["topo_digest"] == self.TOPO.digest()
        assert rec["knobs"] == pm.knob_values()
        got = store.lookup("m" * 64, pm.space())
        assert got is not None and got[1] is True
        assert got[0]["best_x"] == rec["best_x"]

    def test_stale_topo_digest_rejected(self, tmp_path):
        pm, _ = _converged_store(tmp_path, self.TOPO)
        path = tmp_path / record_filename("m" * 64, self.TOPO.digest())
        rec = json.loads(path.read_text())
        rec["topo_digest"] = "0" * 64     # stale: some other fabric
        path.write_text(json.dumps(rec))
        store = TuningStore(str(tmp_path), self.TOPO, rank=0)
        assert store.lookup("m" * 64, pm.space()) is None

    def test_model_sig_mismatch_rejected(self, tmp_path):
        pm, store = _converged_store(tmp_path, self.TOPO)
        # same leading filename chars, different full digest inside
        other = "m" * 16 + "x" * 48
        assert store.lookup(other, pm.space()) is None

    def test_changed_space_rejected(self, tmp_path):
        pm, store = _converged_store(tmp_path, self.TOPO)
        space = pm.space()
        space["categorical"].append(["new_knob", [False, True]])
        assert store.lookup("m" * 64, space) is None

    def test_unknown_version_rejected(self, tmp_path):
        pm, store = _converged_store(tmp_path, self.TOPO)
        path = tmp_path / record_filename("m" * 64, self.TOPO.digest())
        rec = json.loads(path.read_text())
        rec["version"] = 999
        path.write_text(json.dumps(rec))
        assert store.lookup("m" * 64, pm.space()) is None

    def test_nearest_key_prefers_closest_world(self, tmp_path):
        space = None
        for size, local in ((2, 1), (8, 2)):
            topo = Topology(size=size, local_size=local, platform="cpu")
            pm, _ = _converged_store(tmp_path, topo)
            space = pm.space()
        # live world np=4: nearest stored world by log2 distance is 2
        # (|log2(4/2)|=1 == |log2(8/4)|... both 1 -> local_size tiebreak
        # favors neither; larger world wins ties) — use np=3 so the
        # distance is unambiguous: |log2(3/2)|=0.58 < |log2(8/3)|=1.4
        live = Topology(size=3, local_size=1, platform="cpu")
        store = TuningStore(str(tmp_path), live, rank=0)
        got = store.lookup("m" * 64, space)
        assert got is not None
        rec, exact = got
        assert exact is False
        assert rec["topology"]["size"] == 2

    def test_nearest_requires_same_platform(self, tmp_path):
        pm, _ = _converged_store(tmp_path, self.TOPO)
        live = Topology(size=4, local_size=1, platform="tpu")
        store = TuningStore(str(tmp_path), live, rank=0)
        assert store.lookup("m" * 64, pm.space()) is None

    def test_corrupt_file_is_a_miss(self, tmp_path):
        pm, store = _converged_store(tmp_path, self.TOPO)
        path = tmp_path / record_filename("m" * 64, self.TOPO.digest())
        path.write_text("{not json")
        assert store.lookup("m" * 64, pm.space()) is None

    def test_non_root_never_writes(self, tmp_path):
        store = TuningStore(str(tmp_path / "sub"), self.TOPO, rank=1)
        assert store.save({"model_sig": "m" * 64}) is None
        assert not (tmp_path / "sub").exists()

    def test_kv_round_trip(self, tmp_path):
        from horovod_tpu.runner.http_server import KVStoreServer
        server = KVStoreServer()
        port = server.start()
        try:
            kv = ("127.0.0.1", port)
            topo = self.TOPO
            pm = _pm(tune_tree_threshold=True)
            # KV-only store (no directory): save publishes, lookup reads
            store = TuningStore(None, topo, rank=0, kv=kv, kv_timeout=5.0)
            pm.attach_persistence(store)
            pm._model_sig = "k" * 64
            _drive_to_convergence(pm)
            fresh = TuningStore(None, topo, rank=0, kv=kv, kv_timeout=5.0)
            got = fresh.lookup("k" * 64, pm.space())
            assert got is not None and got[1] is True
            assert got[0]["knobs"] == pm.knob_values()
        finally:
            server.stop()


class TestWarmStart:
    TOPO = Topology(size=2, local_size=1, platform="cpu")

    def _space_kw(self):
        return dict(categorical=[("collective_algo", ("auto", "flat"))],
                    tune_tree_threshold=True)

    def test_exact_warm_start_converges_in_one_cycle(self, tmp_path):
        pm, _ = _converged_store(tmp_path, self.TOPO)
        stored_samples = pm.n_samples_taken
        fresh = _pm(warmup_samples=3, **self._space_kw())
        fresh.attach_persistence(TuningStore(str(tmp_path), self.TOPO,
                                             rank=0))
        fresh.maybe_warm_start("m" * 64)
        assert fresh.warm_start_kind == "exact"
        # the stored winner is adopted immediately...
        assert fresh.knob_values() == pm.knob_values()
        assert fresh.active
        # ...and ONE sample confirms convergence (warmup waived): the
        # acceptance bound, asserted by the samples counter
        fresh._step_start = time.perf_counter() - 0.01
        fresh.step_mark(4 * MB)
        fresh._step_start -= 0.01
        fresh.step_mark(4 * MB)
        assert not fresh.active
        assert fresh.n_samples_taken - stored_samples <= 1
        assert fresh.knob_values() == pm.knob_values()

    def test_nearest_key_seeds_but_retunes(self, tmp_path):
        pm, _ = _converged_store(tmp_path, self.TOPO)
        live = Topology(size=4, local_size=1, platform="cpu")
        fresh = _pm(**self._space_kw())
        fresh.attach_persistence(TuningStore(str(tmp_path), live, rank=0))
        fresh.maybe_warm_start("m" * 64)
        assert fresh.warm_start_kind == "nearest"
        assert fresh.active
        assert fresh.n_samples_taken == 0    # no foreign scores replayed
        assert fresh.knob_values() == pm.knob_values()

    def test_dimension_mismatch_ignored(self, tmp_path):
        pm, _ = _converged_store(tmp_path, self.TOPO)
        path = tmp_path / record_filename("m" * 64, self.TOPO.digest())
        rec = json.loads(path.read_text())
        rec["best_x"] = rec["best_x"][:-1]    # space says 5 dims, x has 4
        path.write_text(json.dumps(rec))
        fresh = _pm(**self._space_kw())
        fresh.attach_persistence(TuningStore(str(tmp_path), self.TOPO,
                                             rank=0))
        fresh.maybe_warm_start("m" * 64)
        assert fresh.warm_start_kind == "none"

    def test_miss_leaves_cold_start(self, tmp_path):
        fresh = _pm(**self._space_kw())
        fresh.attach_persistence(TuningStore(str(tmp_path), self.TOPO,
                                             rank=0))
        fresh.maybe_warm_start("q" * 64)
        assert fresh.warm_start_kind == "none"
        assert fresh.active


# ---------------------------------------------------------------------------
# engine integration: warm-start round trip, fallback, model signature
# ---------------------------------------------------------------------------

def _autotune_env(tmp_path, extra=None):
    env = {"HOROVOD_AUTOTUNE": "1",
           "HOROVOD_AUTOTUNE_WARMUP_SAMPLES": "0",
           "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE": "1",
           "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES": "3",
           "HOROVOD_TPU_TUNE_PERSIST_DIR": str(tmp_path)}
    env.update(extra or {})
    return env


class TestEngineIntegration:
    def _drive(self, hvd, shapes=((64, 64),), steps=12, tag="wf"):
        from horovod_tpu.core.state import global_state
        pm = global_state().parameter_manager
        grads = [np.ones(s, np.float32) for s in shapes]
        for i in range(steps):
            hs = hvd.grouped_allreduce_async(grads, name=f"{tag}{i}")
            for h in hs:
                hvd.synchronize(h)
            if pm is not None and not pm.active:
                break
        return pm

    def _with_env(self, env, fn):
        import horovod_tpu as hvd
        old = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        try:
            hvd.shutdown()
            hvd.init()
            return fn(hvd)
        finally:
            for k, v in old.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            hvd.shutdown()
            hvd.init()

    def test_warm_start_round_trip_through_engine(self, tmp_path):
        """tune → persist → fresh engine loads by digest → skips
        exploration (the acceptance criterion end to end)."""
        env = _autotune_env(tmp_path)

        def first_run(hvd):
            from horovod_tpu.core.state import global_state
            pm = self._drive(hvd)
            assert not pm.active, "tuner should have converged"
            eng = global_state().engine
            assert eng.model_signature() is not None
            return (pm.n_samples_taken, pm.knob_values(),
                    eng.model_signature())

        stored_samples, knobs, sig = self._with_env(env, first_run)
        files = [f for f in os.listdir(tmp_path) if f.endswith(".json")]
        assert len(files) == 1

        def second_run(hvd):
            from horovod_tpu.core.state import global_state
            pm = self._drive(hvd, steps=4)
            assert global_state().engine.model_signature() == sig
            return (pm.warm_start_kind, pm.n_samples_taken, pm.active,
                    pm.knob_values())

        kind, samples, active, knobs2 = self._with_env(env, second_run)
        assert kind == "exact"
        assert not active
        # <= 1 new sample past the persisted record: exploration skipped
        assert samples - stored_samples <= 1
        assert knobs2 == knobs

    def test_different_model_is_a_miss(self, tmp_path):
        env = _autotune_env(tmp_path)
        self._with_env(env, lambda hvd: self._drive(hvd))

        def second_run(hvd):
            pm = self._drive(hvd, shapes=((16, 16), (32,)), steps=3,
                             tag="other")
            return pm.warm_start_kind

        assert self._with_env(env, second_run) == "none"

    def test_probe_disabled_falls_back_to_nominal(self):
        """HOROVOD_TPU_CALIBRATE unset: the engine keeps the nominal
        tables and selection still works — the documented fallback."""
        import horovod_tpu as hvd
        from horovod_tpu.core.state import global_state
        hvd.init()
        eng = global_state().engine
        assert eng.topology.calibrated is False
        assert eng.config.hier_threshold_bytes == 0
        out = np.asarray(hvd.allreduce(np.ones(4, np.float32),
                                       name="nom.a", op=hvd.Sum))
        assert out[0] == hvd.size()

    def test_calibrate_on_single_rank_world_is_noop(self):
        """size<=1: the probe is skipped ("world too small"), nominal
        tables stay, init succeeds."""
        import horovod_tpu as hvd
        env = {"HOROVOD_TPU_CALIBRATE": "1"}

        def check(hvd):
            from horovod_tpu.core.state import global_state
            eng = global_state().engine
            assert eng.topology.calibrated is False
            return True

        assert self._with_env(env, check)

    def test_model_signature_is_shape_stable(self):
        import horovod_tpu as hvd
        from horovod_tpu.core.state import global_state
        hvd.shutdown()
        hvd.init()
        try:
            eng = global_state().engine
            assert eng.model_signature() is None
            grads = [np.ones((8, 8), np.float32), np.ones(3, np.float32)]
            for h in hvd.grouped_allreduce_async(grads, name="sig0"):
                hvd.synchronize(h)
            sig = eng.model_signature()
            assert sig is not None
            # later steps with the same layout never move the signature
            for h in hvd.grouped_allreduce_async(grads, name="sig1"):
                hvd.synchronize(h)
            assert eng.model_signature() == sig
        finally:
            hvd.shutdown()
            hvd.init()


# ---------------------------------------------------------------------------
# in-process probe smoke (perf-marked: builds + runs the real probe
# programs on the 8-device CPU world, no timing assertions)
# ---------------------------------------------------------------------------

class _ProbeWorld:
    """Just enough engine surface for probe_link_times/agree_times: an
    8-device single-process world where 'to_global' replicates the
    payload across the device mesh (each device plays one rank)."""

    def __init__(self, local_size=1):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        from horovod_tpu.parallel.mesh import detect_topology
        devs = jax.devices()
        self._n = len(devs)
        self._mesh = Mesh(np.array(devs), ("world",))
        self._sh = NamedSharding(self._mesh, P("world"))
        self._jnp = jnp
        self.topology = detect_topology(size=self._n,
                                        local_size=local_size,
                                        devices=devs)
        self.backend = self

    @property
    def group_mesh(self):
        return self._mesh

    def size(self):
        return self._n

    def to_global(self, x):
        import jax
        return jax.device_put(
            self._jnp.broadcast_to(x, (self._n,) + tuple(x.shape)),
            self._sh)

    def _hierarchical_ok(self):
        return self.topology.hierarchical_ok

    def _exchange_sizes(self, vec):
        return np.asarray(vec)[None]     # one "rank"


@pytest.mark.perf
def test_probe_fits_real_programs():
    """The real probe: build + run the per-class probe programs on the
    8-device world (local_size=4 so flat, tree AND hierarchical classes
    all execute), fit, derive — structure only, no timing assertions."""
    from horovod_tpu.autotune.calibration import (agree_times,
                                                  fit_measured_topology,
                                                  probe_link_times)
    world = _ProbeWorld(local_size=4)
    assert world.topology.hierarchical_ok
    bands = (16 * 1024, 64 * 1024, 256 * 1024)
    local = probe_link_times(world, bands=bands)
    assert set(local) == {"flat", "tree", "hierarchical",
                          A2A_CLASS_FLAT, A2A_CLASS_HIER}
    assert all(len(v) == len(bands) and all(t > 0 for t in v)
               for v in local.values())
    agreed = agree_times(world, local)
    # one participant: the cross-rank median is the local reading,
    # modulo the int-nanosecond exchange grid
    for k in local:
        assert np.allclose(agreed[k], local[k], atol=1e-6)
    m = fit_measured_topology(world.topology, agreed, bands=bands)
    assert m.calibrated
    assert m.ici_gbps > 0 and m.dcn_gbps > 0
    tree_thr, hier_thr = derived_thresholds(m)
    assert TREE_THRESHOLD_MIN <= tree_thr <= TREE_THRESHOLD_MAX
    assert 0 <= hier_thr <= HIER_THRESHOLD_MAX
    # both alltoall legs probed on this world: a measured crossover
    a2a_thr = derived_alltoall_threshold_bytes(m)
    assert a2a_thr is not None
    assert 0 <= a2a_thr <= HIER_THRESHOLD_MAX


# ---------------------------------------------------------------------------
# gap attribution (ISSUE 14 satellite): live 2-rank trace -> four sinks
# ---------------------------------------------------------------------------

class TestGapAttribution:
    def _live_two_rank_events(self, late=0.02):
        """A genuine 2-rank merged trace built from real TraceRecorders
        (the test_trace pattern): 5 steps, one correlated collective per
        step, rank 1 arriving ``late`` seconds behind rank 0."""
        import contextlib
        import time as _t
        from unittest import mock
        from horovod_tpu.trace import TraceRecorder, merge_segments

        @contextlib.contextmanager
        def _frozen(at):
            real = _t.monotonic
            with mock.patch.object(_t, "monotonic", lambda: at):
                yield
            assert _t.monotonic is real

        segs = {}
        base = _t.monotonic()
        for r in (0, 1):
            rec = TraceRecorder(rank=r)
            shift = late if r == 1 else 0.0
            for i in range(5):
                with _frozen(base + i * 0.1 + shift):
                    rec.record_step(begin=True)
                    rec.record_enqueue("g0", "allreduce", 64, 0)
                with _frozen(base + i * 0.1 + shift + 0.004):
                    rec.record_dispatch("g0", "XLA_DISPATCH", 0.004)
                with _frozen(base + i * 0.1 + max(shift, late) + 0.03):
                    rec.record_done("g0")
                with _frozen(base + i * 0.1 + shift + 0.08):
                    rec.record_step(begin=False)
            rec.add_beacon(base, 777.0 + base, 0.0)
            segs[r] = rec.segment()
        return merge_segments(segs)

    def test_four_sinks_partition_step_time(self):
        sys.path.insert(0, TOOLS)
        try:
            import trace_report
            events = self._live_two_rank_events()
            gaps = trace_report.gap_attribution(events)
        finally:
            sys.path.remove(TOOLS)
        assert set(gaps) == {0, 1}
        for pid, row in gaps.items():
            assert row["steps"] == 5
            total = (row["compute_us"] + row["dispatch_us"]
                     + row["wire_us"] + row["straggler_wait_us"])
            assert total == pytest.approx(row["total_us"], rel=1e-6)
            assert row["dispatch_us"] > 0
            assert set(row["pct"]) == {"compute", "dispatch", "wire",
                                       "straggler_wait"}
        # rank 0 arrived first every step: the straggler wait is ITS
        # time lost to rank 1 (5 steps x ~20 ms); rank 1 never waits
        assert gaps[0]["straggler_wait_us"] == pytest.approx(
            5 * 0.02e6, rel=0.2)
        assert gaps[1]["straggler_wait_us"] == 0.0

    def test_report_renders_gap_section(self, tmp_path, capsys):
        from horovod_tpu.trace import render_cluster_trace
        sys.path.insert(0, TOOLS)
        try:
            import trace_report
            events = self._live_two_rank_events()
            path = tmp_path / "merged.json"
            path.write_text(json.dumps({"traceEvents": events}))
            rc = trace_report.main([str(path)])
            out = capsys.readouterr().out
        finally:
            sys.path.remove(TOOLS)
        assert rc == 0
        assert "gap attribution" in out
        assert "compute=" in out and "straggler=" in out

    def test_analyze_includes_gap_attribution(self):
        sys.path.insert(0, TOOLS)
        try:
            import trace_report
            rep = trace_report.analyze(self._live_two_rank_events())
        finally:
            sys.path.remove(TOOLS)
        assert "gap_attribution" in rep
        assert rep["gap_attribution"][0]["pct"]["compute"] >= 0


# ---------------------------------------------------------------------------
# bench provenance (ISSUE 14 satellite)
# ---------------------------------------------------------------------------

class TestKnobProvenance:
    def test_config_records_env_vs_default(self, monkeypatch):
        from horovod_tpu.common.env import Config
        monkeypatch.setenv("HOROVOD_TPU_TREE_THRESHOLD_BYTES", "8192")
        cfg = Config.from_env()
        assert cfg.provenance["tree_threshold_bytes"] == "env-forced"
        assert cfg.provenance["fusion_threshold_bytes"] == "default"

    def test_bench_report_shape(self):
        sys.path.insert(0, os.path.dirname(TOOLS))
        try:
            import bench
            rep = bench.knob_provenance_report()
        finally:
            sys.path.remove(os.path.dirname(TOOLS))
        prov = rep["knob_provenance"]
        assert "tree_threshold_bytes" in prov
        assert set(prov["tree_threshold_bytes"]) == {"value", "source"}
        assert "link_table" in rep or "autotune_state" in rep or True

    def test_calibration_sets_provenance(self, tmp_path):
        """engine._apply_calibration flips tree_threshold provenance to
        'calibrated' (unit-level: drive the config mutation the way the
        engine does, via derived thresholds on a measured overlay)."""
        from horovod_tpu.common.env import Config
        cfg = Config.from_env()
        assert cfg.provenance["tree_threshold_bytes"] == "default"
        base = Topology(size=8, local_size=4, platform="cpu")
        m = measured_topology(base, 6.0, 0.8, 10.0,
                              {"flat": (1e-4, 1e9),
                               "hierarchical": (3e-4, 4e9)})
        tree_thr, hier_thr = derived_thresholds(m)
        if cfg.provenance.get("tree_threshold_bytes") != "env-forced":
            cfg.tree_threshold_bytes = tree_thr
            cfg.provenance["tree_threshold_bytes"] = "calibrated"
        cfg.hier_threshold_bytes = hier_thr
        assert cfg.provenance["tree_threshold_bytes"] == "calibrated"
        assert cfg.tree_threshold_bytes == tree_thr
