"""Async sharded checkpointing (ISSUE 9): serialization/reshard math,
manifests + the commit barrier, the async manager (double-buffering,
peer-redundant restore, GC, failpoints), the chunked KV transfer, and
the TPUState durable delegation."""

import json
import os
import shutil
import time

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu import faults
from horovod_tpu.checkpoint import (CheckpointManager,
                                    CheckpointRestoreError, build_manifest,
                                    checksum, generation_complete,
                                    reshard_ranges, validate_manifest,
                                    zero1_reshard)
from horovod_tpu.checkpoint import manifest as mf
from horovod_tpu.checkpoint import shard_io
from horovod_tpu.metrics import registry


@pytest.fixture(autouse=True)
def _disarm():
    faults.disarm()
    yield
    faults.disarm()


def _tree(seed=0, kb=4):
    rng = np.random.RandomState(seed)
    return {"w": rng.rand(kb * 64, 4).astype(np.float32),
            "b": rng.rand(7).astype(np.float32),
            "n": np.int32(3)}


def _write_world(d, tree, n, step=1, redundancy=1, kv=None, extras=None):
    mgrs = [CheckpointManager(d, rank=r, world_size=n,
                              redundancy=redundancy, kv=kv)
            for r in range(n)]
    try:
        for m in mgrs:
            assert m.snapshot(tree, step=step, extras=extras)
        for m in mgrs:
            assert m.wait_idle(60)
    finally:
        for m in mgrs:
            m.close(flush=False)
    return mgrs


# ---------------------------------------------------------------------------
# shard_io: the flat-stream layout + N→M re-slice math
# ---------------------------------------------------------------------------

class TestShardIO:
    def test_encode_decode_round_trip(self):
        tree = _tree()
        import jax
        leaves, _ = jax.tree_util.tree_flatten(tree)
        leaves = [np.asarray(l) for l in leaves]
        header = shard_io.make_header(leaves, step=1, world_version=0,
                                      world_size=4)
        stream = shard_io.encode_leaves(leaves)
        assert len(stream) == header["total_bytes"]
        out = shard_io.decode_leaves(stream, header)
        for a, b in zip(leaves, out):
            np.testing.assert_array_equal(a, b)

    def test_shards_cover_stream_with_padding(self):
        stream = bytes(range(256)) * 3  # 768 bytes
        for n in (1, 2, 3, 5, 7):
            shards = [shard_io.shard_of(stream, r, n) for r in range(n)]
            assert len({len(s) for s in shards}) == 1  # uniform shard size
            joined = b"".join(shards)
            assert joined[:len(stream)] == stream
            assert set(joined[len(stream):]) <= {0}  # zero tail padding

    @pytest.mark.parametrize("old_n,new_n", [(4, 2), (2, 4), (4, 1),
                                             (1, 4), (3, 5), (5, 3)])
    def test_reshard_ranges_exact(self, old_n, new_n):
        """The elastic-resize re-slice: concatenating every new rank's
        ranges, read out of the old shards, reproduces the stream."""
        stream = os.urandom(1037)  # awkward size: padding on both worlds
        old = [shard_io.shard_of(stream, r, old_n) for r in range(old_n)]
        rebuilt = b""
        for nr in range(new_n):
            for old_rank, off, length in reshard_ranges(
                    len(stream), old_n, nr, new_n):
                rebuilt += old[old_rank][off:off + length]
        assert rebuilt == stream

    def test_zero1_state_bucket_assignment(self):
        """Optax-style state leaf runs (mu[b0..], nu[b0..]) map onto
        buckets cyclically per run; scalars stay replicated. Two buckets
        share a shard size — the ambiguous case the run rule resolves."""
        buckets = [{"shard": 5}, {"shard": 5}, {"shard": 3}]
        leaves = [np.zeros(()),                       # count -> None
                  np.zeros(5), np.zeros(5), np.zeros(3),   # mu run
                  np.zeros(5), np.zeros(5), np.zeros(3)]   # nu run
        got = shard_io._assign_state_buckets(leaves, buckets)
        assert got == [None, 0, 1, 2, 0, 1, 2]

    def test_zero1_reshard_parity(self):
        """N=3 → M=2: reassembled full buckets equal the logical flat
        params, and the new shards re-slice them exactly (adam momenta
        included)."""
        import optax
        layout = [((0, 1), (10, 7), 17, 6), ((2,), (7,), 7, 3)]
        rng = np.random.RandomState(1)
        full0, full1 = rng.rand(18).astype(np.float32), \
            rng.rand(9).astype(np.float32)
        full0[17:] = 0
        full1[7:] = 0
        opt = optax.adam(1e-3)
        payloads, header = {}, None
        for r in range(3):
            shards = [full0[r * 6:(r + 1) * 6], full1[r * 3:(r + 1) * 3]]
            st = opt.init([np.asarray(s) for s in shards])
            header = shard_io.zero1_header(layout, shards,
                                           _flatten(st), step=2,
                                           world_version=1, world_size=3)
            payloads[r] = shard_io.zero1_payload(shards, _flatten(st))
        for new_rank in range(2):
            re = zero1_reshard(header, payloads, new_rank, 2)
            np.testing.assert_array_equal(re["full_buckets"][0], full0[:17])
            np.testing.assert_array_equal(re["full_buckets"][1], full1[:7])
            # new world: bucket0 shard = ceil(17/2) = 9
            assert re["shards"][0].shape == (9,)
            pad0 = np.concatenate([full0[:17], np.zeros(1, np.float32)])
            np.testing.assert_array_equal(
                re["shards"][0], pad0[new_rank * 9:(new_rank + 1) * 9])

    def test_zero1_reshard_missing_rank_raises(self):
        layout = [((0,), (4,), 4, 2)]
        shards = [np.arange(2, dtype=np.float32)]
        header = shard_io.zero1_header(layout, shards, [], step=1,
                                       world_version=0, world_size=2)
        with pytest.raises(ValueError, match="missing"):
            zero1_reshard(header, {0: shard_io.zero1_payload(shards, [])},
                          0, 1)


def _flatten(tree):
    import jax
    return [np.asarray(l) for l in jax.tree_util.tree_flatten(tree)[0]]


# ---------------------------------------------------------------------------
# manifests + commit barrier
# ---------------------------------------------------------------------------

class TestManifest:
    def _man(self, rank, n=2, step=1, wv=0, digest="d" * 8, cs=None):
        cs = cs or {rank: "a" * 64}
        return build_manifest(rank, step=step, world_version=wv,
                              world_size=n, layout_digest=digest,
                              shard_checksums=cs,
                              shard_bytes={k: 10 for k in cs},
                              holds=list(cs))

    def test_schema_round_trip(self):
        m = json.loads(json.dumps(self._man(0)))
        assert validate_manifest(m) == []

    def test_schema_rejects(self):
        m = self._man(0)
        del m["layout_digest"]
        assert any("layout_digest" in e for e in validate_manifest(m))
        m = self._man(0)
        m["shard_checksums"] = {"0": "nothex"}
        assert any("sha256" in e for e in validate_manifest(m))
        m = self._man(1, n=1)
        assert any("outside world" in e for e in validate_manifest(m))

    def test_barrier_complete_and_stale_wv(self):
        mans = {0: self._man(0), 1: self._man(1, cs={1: "b" * 64})}
        ok, errs = generation_complete(mans)
        assert ok, errs
        mans[1]["world_version"] = 7
        ok, errs = generation_complete(mans)
        assert not ok and any("stale world_version" in e for e in errs)

    def test_barrier_partial_and_checksum_mismatch(self):
        ok, errs = generation_complete({0: self._man(0)})
        assert not ok and any("missing manifests" in e for e in errs)
        mans = {0: self._man(0, cs={0: "a" * 64, 1: "c" * 64}),
                1: self._man(1, cs={1: "b" * 64})}
        ok, errs = generation_complete(mans)
        assert not ok and any("checksum mismatch" in e for e in errs)

    def test_restorable_covers_lost_host(self):
        """One manifest gone (lost host) but its shard held by the
        survivor → restorable; shard held by nobody → not."""
        mans = {0: self._man(0, cs={0: "a" * 64, 1: "b" * 64})}
        mans[0]["holds"] = [0, 1]
        ok, errs = mf.generation_restorable(mans)
        assert ok, errs
        lone = {0: self._man(0)}
        ok, errs = mf.generation_restorable(lone)
        assert not ok and any("held by no surviving rank" in e
                              for e in errs)


# ---------------------------------------------------------------------------
# CheckpointManager
# ---------------------------------------------------------------------------

class TestManager:
    def test_round_trip_with_template_and_extras(self, tmp_path):
        tree = _tree()
        _write_world(str(tmp_path), tree, n=3, step=4,
                     extras={"batch": 9})
        m = CheckpointManager(str(tmp_path), rank=0, world_size=3)
        try:
            res = m.restore_latest(template=tree)
            assert res.step == 4 and res.mode == "replicated"
            assert res.extras == {"batch": 9}
            np.testing.assert_array_equal(res.tree["w"], tree["w"])
            assert int(res.tree["n"]) == 3
        finally:
            m.close(flush=False)

    def test_snapshot_is_async_and_double_buffered(self, tmp_path):
        """The step path never blocks on a write: with the writer held
        at the failpoint, extra requests replace the pending slot
        (counted skipped) and snapshot() stays ~instant."""
        reg = registry()
        skipped0 = reg.counter("hvd_tpu_ckpt_snapshots_total").value(
            outcome="skipped")
        faults.arm("checkpoint.write=1*delay(0.5)")
        m = CheckpointManager(str(tmp_path), rank=0, world_size=1)
        try:
            tree = _tree()
            t0 = time.perf_counter()
            for s in range(1, 6):
                m.snapshot(tree, step=s)
            stall = time.perf_counter() - t0
            assert stall < 0.3, f"snapshot() blocked the step path: {stall}"
            assert m.wait_idle(30)
            assert reg.counter("hvd_tpu_ckpt_snapshots_total").value(
                outcome="skipped") > skipped0
            # the newest request won the pending slot
            assert m.last_written_step == 5
        finally:
            m.close(flush=False)

    def test_write_drop_failpoint_never_commits(self, tmp_path):
        """drop() on checkpoint.write models a lost snapshot: no files,
        no manifest — and restore refuses the void loudly."""
        reg = registry()
        failed0 = reg.counter("hvd_tpu_ckpt_snapshots_total").value(
            outcome="failed")
        faults.arm("checkpoint.write=1*drop()")
        m = CheckpointManager(str(tmp_path), rank=0, world_size=1)
        try:
            m.snapshot(_tree(), step=1)
            assert m.wait_idle(30)
            assert reg.counter("hvd_tpu_ckpt_snapshots_total").value(
                outcome="failed") == failed0 + 1
            assert m.latest_generation() is None
            with pytest.raises(CheckpointRestoreError):
                m.restore_latest()
            # the next (unarmed) snapshot commits normally
            m.snapshot(_tree(), step=2)
            assert m.wait_idle(30)
            assert m.latest_generation()[0] == 2
        finally:
            m.close(flush=False)

    def test_restore_failpoint_surfaces(self, tmp_path):
        _write_world(str(tmp_path), _tree(), n=1)
        faults.arm("checkpoint.restore=1*raise(HorovodInternalError)")
        m = CheckpointManager(str(tmp_path), rank=0, world_size=1)
        try:
            from horovod_tpu.common.exceptions import HorovodInternalError
            with pytest.raises(HorovodInternalError):
                m.restore_latest()
            faults.disarm()
            assert m.restore_latest(template=_tree()).step == 1
        finally:
            m.close(flush=False)

    def test_peer_redundant_restore_disk(self, tmp_path):
        """A lost host (rank dir deleted): its shard restores from the
        neighbor's replica; with TWO of three hosts lost, redundancy 1
        is exceeded and restore refuses."""
        tree = _tree(kb=8)
        _write_world(str(tmp_path), tree, n=3)
        shutil.rmtree(tmp_path / "rank1")
        m = CheckpointManager(str(tmp_path), rank=0, world_size=3)
        try:
            res = m.restore_latest(template=tree)
            np.testing.assert_array_equal(res.tree["w"], tree["w"])
        finally:
            m.close(flush=False)
        shutil.rmtree(tmp_path / "rank2")
        m = CheckpointManager(str(tmp_path), rank=0, world_size=3)
        try:
            with pytest.raises(CheckpointRestoreError):
                m.restore_latest(template=tree)
        finally:
            m.close(flush=False)

    def test_corrupt_replica_rejected(self, tmp_path):
        """A bit-flipped shard fails the manifest checksum at restore."""
        tree = _tree()
        _write_world(str(tmp_path), tree, n=2)
        shutil.rmtree(tmp_path / "rank1")
        # corrupt rank 0's replica of shard 1
        path = tmp_path / "rank0" / "gen1" / "shard_1.bin"
        data = bytearray(path.read_bytes())
        data[0] ^= 0xFF
        path.write_bytes(bytes(data))
        m = CheckpointManager(str(tmp_path), rank=0, world_size=2)
        try:
            with pytest.raises(CheckpointRestoreError,
                               match="checksum mismatch"):
                m.restore_latest(template=tree)
        finally:
            m.close(flush=False)

    def test_reshard_restore_n4_to_n2_and_slice(self, tmp_path):
        """ISSUE acceptance: a generation written at np=4 restores at
        np=2 (and np=1), and restore_shard_slice's byte ranges re-slice
        the stream against the new world's shard_spec padding."""
        import jax
        tree = _tree(seed=3)
        _write_world(str(tmp_path), tree, n=4)
        for new_n in (2, 1):
            m = CheckpointManager(str(tmp_path), rank=0, world_size=new_n)
            try:
                res = m.restore_latest(template=tree)
                for a, b in zip(jax.tree_util.tree_leaves(tree),
                                jax.tree_util.tree_leaves(res.tree)):
                    np.testing.assert_array_equal(np.asarray(a),
                                                  np.asarray(b))
                stream = shard_io.encode_leaves(
                    [np.asarray(l)
                     for l in jax.tree_util.tree_leaves(tree)])
                joined = b"".join(m.restore_shard_slice(r, new_n)
                                  for r in range(new_n))
                assert joined[:len(stream)] == stream
            finally:
                m.close(flush=False)

    def test_gc_keeps_newest_and_drops_partials(self, tmp_path):
        reg = registry()
        gc0 = reg.counter("hvd_tpu_ckpt_gc_total").total()
        m = CheckpointManager(str(tmp_path), rank=0, world_size=1, keep=2)
        try:
            # a partial generation (no manifest): a crashed write
            partial = tmp_path / "rank0" / "gen1"
            partial.mkdir(parents=True)
            (partial / "shard_0.bin").write_bytes(b"junk")
            for s in (2, 3, 4):
                m.snapshot(_tree(seed=s), step=s)
                assert m.wait_idle(30)
            gens = sorted(os.listdir(tmp_path / "rank0"))
            assert gens == ["gen3", "gen4"], gens
            assert reg.counter("hvd_tpu_ckpt_gc_total").total() > gc0
        finally:
            m.close(flush=False)

    def test_zero1_manager_round_trip_with_optimizer(self, tmp_path):
        """End-to-end ZeRO-1 durable path at the optimizer level: the
        sharded state written at np=1 restores through
        restore_from_durable with momenta intact."""
        import jax.numpy as jnp
        import optax
        from horovod_tpu.optimizer import DistributedEagerOptimizer
        hvd.init()
        opt = DistributedEagerOptimizer(optax.adam(1e-3), sharded=True,
                                        op=hvd.Sum)
        params = {"w": jnp.arange(12, dtype=jnp.float32),
                  "b": jnp.ones((5,), jnp.float32)}
        state = opt.init(params)
        # make momenta non-trivial
        grads = {"w": jnp.ones((12,), jnp.float32),
                 "b": jnp.full((5,), 2.0, jnp.float32)}
        params2, state2 = opt.update_and_apply(grads, state, params)
        shards, inner, layout = opt.checkpoint_payload(state2, params2)
        m = CheckpointManager(str(tmp_path), rank=0, world_size=1)
        try:
            assert m.snapshot_zero1(shards, inner, layout, step=1)
            assert m.wait_idle(30)
            res = m.restore_latest()
            assert res.mode == "zero1"
            r_params, r_state = opt.restore_from_durable(res.tree, params2)
            for a, b in zip(_flatten(params2), _flatten(r_params)):
                np.testing.assert_array_equal(a, b)
            for a, b in zip(_flatten(state2), _flatten(r_state)):
                np.testing.assert_array_equal(a, b)
            # the restored state drives the same next step bitwise
            p3a, s3a = opt.update_and_apply(grads, state2, params2)
            p3b, s3b = opt.update_and_apply(grads, r_state, r_params)
            for a, b in zip(_flatten(p3a), _flatten(p3b)):
                np.testing.assert_array_equal(a, b)
        finally:
            m.close(flush=False)


# ---------------------------------------------------------------------------
# chunked large-value KV transfer + KV-backed peer restore
# ---------------------------------------------------------------------------

class TestKVTransfer:
    @pytest.fixture
    def kv_server(self):
        from horovod_tpu.runner.http_server import KVStoreServer
        server = KVStoreServer(("127.0.0.1", 0))
        server.start()
        yield server
        faults.disarm()
        server.stop()

    def test_chunked_round_trip_and_delete(self, kv_server):
        from horovod_tpu.runner.http_client import (
            delete_large_value, put_large_value, read_large_value)
        kv = ("127.0.0.1", kv_server.port)
        value = os.urandom(300_000)
        n = put_large_value(*kv, "ckptshard", "g1.r0", value,
                            chunk_bytes=65536)
        assert n == 5  # ceil(300000/65536)
        got = read_large_value(*kv, "ckptshard", "g1.r0", timeout=10)
        assert got == value
        delete_large_value(*kv, "ckptshard", "g1.r0")
        with pytest.raises(TimeoutError):
            read_large_value(*kv, "ckptshard", "g1.r0", timeout=0.5)
        # server-side store is clean of chunk keys too
        assert not kv_server.snapshot().get("ckptshard")

    def test_read_retries_torn_write(self, kv_server):
        """Meta present but a chunk inconsistent (torn interleaving):
        the reader retries until the writer completes."""
        import threading
        from horovod_tpu.runner.http_client import (put_data_into_kvstore,
                                                    put_large_value,
                                                    read_large_value)
        kv = ("127.0.0.1", kv_server.port)
        value = os.urandom(100_000)
        import hashlib
        meta = {"chunks": 2, "bytes": len(value),
                "sha256": hashlib.sha256(value).hexdigest(),
                "chunk_bytes": 65536}
        # torn state: meta + first chunk only
        put_data_into_kvstore(*kv, "ckptshard", "k.c0", value[:65536])
        put_data_into_kvstore(*kv, "ckptshard", "k",
                              json.dumps(meta).encode())

        def _complete():
            time.sleep(0.3)
            put_large_value(*kv, "ckptshard", "k", value,
                            chunk_bytes=65536)

        t = threading.Thread(target=_complete)
        t.start()
        try:
            assert read_large_value(*kv, "ckptshard", "k",
                                    timeout=10) == value
        finally:
            t.join()

    def test_kv_only_restore_after_downsize(self, kv_server, tmp_path):
        """An np=3 world's generation lives ONLY in the KV (manifests,
        header, chunked shards) for a restorer with a private directory
        at np=1: the manifest probe must widen past the restorer's own
        world size to the writer world the first hit advertises —
        otherwise ranks >= 1 look unpublished and coverage fails."""
        kv = ("127.0.0.1", kv_server.port)
        tree = _tree(seed=6)
        _write_world(str(tmp_path), tree, n=3, kv=kv)
        m = CheckpointManager(str(tmp_path / "private"), rank=0,
                              world_size=1, kv=kv)
        try:
            res = m.restore_latest(template=tree)
            np.testing.assert_array_equal(res.tree["w"], tree["w"])
            assert res.step == 1
        finally:
            m.close(flush=False)

    def test_kv_mediated_peer_restore(self, kv_server, tmp_path):
        """The wire path proper: rank 1's disk is GONE and the restorer
        has no shared-fs view of it — rank 0 re-publishes its replica to
        the KV during restore, and rank 1 fetches it over the wire."""
        kv = ("127.0.0.1", kv_server.port)
        tree = _tree(seed=5)
        _write_world(str(tmp_path), tree, n=2, kv=kv)
        # wipe the KV (a restarted rendezvous server after preemption)
        # and rank 1's disk
        kv_server.clear_all()
        shutil.rmtree(tmp_path / "rank1")
        # rank 1 restores into a PRIVATE directory: its only route to
        # shard 1 is rank 0's replica via the KV
        m0 = CheckpointManager(str(tmp_path), rank=0, world_size=2,
                               kv=kv)
        lonely = tmp_path / "lonely"
        m1 = CheckpointManager(str(lonely), rank=1, world_size=2, kv=kv,
                               kv_timeout=15.0)
        try:
            # rank 0's restore re-publishes everything it holds — its
            # shards (0 AND the replica of 1), its manifest, the header
            res0 = m0.restore_latest(template=tree)
            np.testing.assert_array_equal(res0.tree["w"], tree["w"])
            res1 = m1.restore_latest(template=tree)
            np.testing.assert_array_equal(res1.tree["w"], tree["w"])
        finally:
            m0.close(flush=False)
            m1.close(flush=False)


# ---------------------------------------------------------------------------
# TPUState durable delegation + elastic integration (single process)
# ---------------------------------------------------------------------------

class TestTPUStateDurable:
    @pytest.fixture
    def ckpt_world(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HOROVOD_TPU_CHECKPOINT_DIR", str(tmp_path))
        hvd.shutdown()
        hvd.init()
        yield tmp_path
        hvd.shutdown()
        monkeypatch.delenv("HOROVOD_TPU_CHECKPOINT_DIR", raising=False)
        hvd.init()

    def test_save_snapshots_and_fresh_state_restores(self, ckpt_world):
        """The durable-restore proof at state level: commit through
        TPUState, then a FRESH state (no in-memory commit — the
        preempted-host case) restores the durable generation bitwise."""
        from horovod_tpu.core.state import global_state
        import jax.numpy as jnp
        mgr = global_state().checkpoint_manager
        assert mgr is not None
        params = {"w": jnp.arange(6, dtype=jnp.float32) * 2}
        state = hvd.elastic.TPUState(params=params, batch=0)
        state.batch = 7
        state.commit()
        assert mgr.wait_idle(30)
        assert mgr.last_written_step == 1
        fresh = hvd.elastic.TPUState(
            params={"w": jnp.zeros(6, jnp.float32)}, batch=0)
        fresh.restore()
        np.testing.assert_array_equal(np.asarray(fresh.params["w"]),
                                      np.arange(6, dtype=np.float32) * 2)
        assert fresh.batch == 7

    def test_in_memory_commit_stays_authoritative(self, ckpt_world):
        """A SURVIVING process restores its own in-memory commit even
        with durable generations on disk (saves precede snapshots, so
        in-memory is never older)."""
        import jax.numpy as jnp
        from horovod_tpu.core.state import global_state
        state = hvd.elastic.TPUState(
            params={"w": jnp.ones(4, jnp.float32)}, batch=0)
        state.batch = 3
        state.commit()
        assert global_state().checkpoint_manager.wait_idle(30)
        # mutate WITHOUT committing, then restore: in-memory commit wins
        state.batch = 99
        state.params = {"w": jnp.zeros(4, jnp.float32)}
        state.restore()
        assert state.batch == 3
        np.testing.assert_array_equal(np.asarray(state.params["w"]),
                                      np.ones(4, np.float32))

    def test_elastic_run_restores_durable_before_first_sync(
            self, ckpt_world):
        """The run-loop integration: @hvd.elastic.run on a fresh state
        picks up the durable generation before training starts, and the
        durable recovery is counted."""
        import jax.numpy as jnp
        from horovod_tpu.core.state import global_state
        reg = registry()
        durable0 = reg.counter("hvd_tpu_elastic_recoveries_total").value(
            kind="durable")
        seed = hvd.elastic.TPUState(
            params={"w": jnp.full((3,), 5.0, jnp.float32)}, batch=0)
        seed.batch = 11
        seed.commit()
        assert global_state().checkpoint_manager.wait_idle(30)

        fresh = hvd.elastic.TPUState(
            params={"w": jnp.zeros(3, jnp.float32)}, batch=0)
        seen = {}

        @hvd.elastic.run
        def train(state):
            seen["batch"] = state.batch
            seen["w"] = np.asarray(state.params["w"]).copy()
            return "done"

        assert train(fresh) == "done"
        assert seen["batch"] == 11
        np.testing.assert_array_equal(seen["w"],
                                      np.full((3,), 5.0, np.float32))
        assert reg.counter("hvd_tpu_elastic_recoveries_total").value(
            kind="durable") == durable0 + 1

    def test_restore_timeline_in_trace_ring(self, tmp_path):
        """The flight-recorder contract: snapshot writes and restores
        record correlated ckpt.* spans into the PR 5 trace ring, so a
        merged trace / flight dump shows the restore timeline."""
        from horovod_tpu.trace import TraceRecorder, merge_segments
        rec = TraceRecorder(rank=0, capacity=256)
        rec.add_beacon(0.0, 1000.0, 0.001)
        m = CheckpointManager(str(tmp_path), rank=0, world_size=1,
                              trace=rec)
        try:
            m.snapshot(_tree(), step=3)
            assert m.wait_idle(30)
            m.restore_latest(template=_tree())
        finally:
            m.close(flush=False)
        events = merge_segments({0: rec.segment()})
        # spans are balanced B/E pairs, correlated via the corr id and
        # carrying the CHECKPOINT kind
        for nm in ("ckpt.write.g3", "ckpt.restore.g3"):
            phs = [e["ph"] for e in events
                   if str(e.get("args", {}).get("corr", ""))
                   .startswith(nm + "#")]
            assert phs.count("B") == 1 and phs.count("E") == 1, (nm,
                                                                 events)
        assert any(e.get("name") == "CHECKPOINT" for e in events)

    def test_interval_hook_snapshots_provider(self, ckpt_world):
        """HOROVOD_TPU_CHECKPOINT_INTERVAL_STEPS path: the engine's
        step hook drives provider snapshots every k completed steps."""
        from horovod_tpu.core.state import global_state
        gs = global_state()
        mgr = gs.checkpoint_manager
        mgr.interval_steps = 2
        tick = {"n": 0}

        def provider():
            tick["n"] += 1
            return {"x": np.arange(3, dtype=np.float32)}, tick["n"]

        mgr.register_provider(provider)
        eng = gs.engine
        for _ in range(4):
            eng.step_begin()
            eng.step_end()
        assert mgr.wait_idle(30)
        assert tick["n"] == 2  # steps 2 and 4
        assert mgr.latest_generation() is not None
