"""Compiled-program structure assertions (VERDICT r2 item 3).

Multi-chip perf can't be *measured* on this rig (one real chip), but the
*structure* of the compiled programs — the thing that determines collective
count and fusion on a real pod — can be asserted on the 8-virtual-device CPU
mesh: grouped_allreduce must compile to one collective per fusion bucket,
hierarchical allreduce must lower to the RS/AG ladder with node-local
``replica_groups``, EP dispatch must be a single all-to-all, and the SPMD
flagship step must contain gradient all-reduces at all.

Reference bar: fusion as *the* latency optimization
(controller.cc:652-773 FuseResponses); hierarchical decomposition
(nccl_operations.cc:180-383).
"""

import re

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from horovod_tpu.common.reduce_ops import ReduceOp
from horovod_tpu.ops import collectives as C


def _world_mesh(n=8):
    devs = jax.devices()[:n]
    return Mesh(np.array(devs), ("world",))


def _hlo(jitted, *args):
    return jitted.lower(*args).compile().as_text()


def _count(pattern, hlo):
    return len(re.findall(pattern, hlo))


def test_fused_allreduce_is_one_collective_per_bucket():
    """50 small tensors packed into one bucket -> exactly ONE all-reduce in
    the optimized HLO (the fusion-buffer guarantee)."""
    mesh = _world_mesh()
    shapes = tuple((7, 3) for _ in range(50))
    fn = C.build_fused_allreduce(mesh, "world", ReduceOp.SUM, shapes,
                                 jnp.float32, 1.0, 1.0, 0)
    total = sum(int(np.prod(s)) for s in shapes)
    packed = jnp.zeros((8, total), jnp.float32)  # stacked (n, total)
    garr = jax.device_put(packed, NamedSharding(mesh, P("world")))
    hlo = _hlo(fn, garr)
    n_ar = _count(r"all-reduce(?:-start)?\(", hlo)
    assert n_ar == 1, f"expected 1 fused all-reduce, found {n_ar}"


def test_bucketing_bounds_collective_count():
    """bucket_by_size: 20 tensors under a threshold that forces 4 buckets ->
    at most 4 collectives across the bucket programs."""
    from horovod_tpu.core.engine import bucket_by_size
    tensors = [jnp.ones((256,), jnp.float32) for _ in range(20)]
    # 256 floats = 1 KiB each; 5 KiB threshold -> 5 per bucket -> 4 buckets
    buckets = bucket_by_size(tensors, 5 * 1024)
    assert len(buckets) == 4
    mesh = _world_mesh()
    total_collectives = 0
    for idxs in buckets:
        shapes = tuple((256,) for _ in idxs)
        fn = C.build_fused_allreduce(mesh, "world", ReduceOp.SUM, shapes,
                                     jnp.float32, 1.0, 1.0, 0)
        packed = jax.device_put(
            jnp.zeros((8, 256 * len(idxs)), jnp.float32),
            NamedSharding(mesh, P("world")))
        total_collectives += _count(r"all-reduce(?:-start)?\(", _hlo(fn, packed))
    assert total_collectives == 4


def test_hierarchical_allreduce_lowers_to_ladder():
    """local_size=4 on 8 devices: reduce-scatter within node, all-reduce
    across nodes, all-gather back — with 2-node replica groups of size 4."""
    mesh = _world_mesh()
    fn = C.build_hierarchical_allreduce(mesh, "world", 4, ReduceOp.SUM,
                                        1.0, 1.0)
    x = jax.device_put(jnp.zeros((64,), jnp.float32),
                       NamedSharding(mesh, P()))
    hlo = _hlo(fn, x)
    # the RS/AG ladder: at least one reduce-scatter and one all-gather (XLA
    # may lower psum_scatter to reduce-scatter or all-reduce+slice depending
    # on backend; accept either spelling but require node-local groups)
    has_ladder = (_count(r"reduce-scatter", hlo) >= 1
                  or _count(r"all-reduce", hlo) >= 2)
    assert has_ladder, "hierarchical program collapsed to a flat all-reduce"
    assert _count(r"all-gather", hlo) >= 1, "missing all-gather stage"
    # node-local replica groups {0..3} {4..7} must appear somewhere
    local_groups = re.search(r"replica_groups=\{\{0,1,2,3\},\{4,5,6,7\}\}",
                             hlo.replace(" ", ""))
    assert local_groups, "no node-local (0-3 / 4-7) replica groups in HLO"


def test_moe_dispatch_is_single_all_to_all():
    """EP token dispatch over the tensor axis: exactly one all-to-all each
    way (dispatch + return), not per-expert sends."""
    from horovod_tpu.parallel.moe import MoEParams, moe_layer_p
    n, d, e, f = 8, 16, 8, 32
    mesh = _world_mesh()
    router = jnp.zeros((d, e), jnp.float32)
    w1 = jnp.zeros((e, d, f), jnp.float32)
    w2 = jnp.zeros((e, f, d), jnp.float32)

    def body(tok, router, w1, w2):
        y, aux = moe_layer_p(tok, MoEParams(router, w1, w2), "world", n,
                             capacity_factor=2.0)
        return y, jax.lax.pmean(aux, "world")

    tok_sh = NamedSharding(mesh, P("world"))
    rep = NamedSharding(mesh, P())
    ep_sh = NamedSharding(mesh, P("world"))
    import functools
    from jax import shard_map
    fn = jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P("world"), P(), P("world"), P("world")),
        out_specs=(P("world"), P())))
    tok = jax.device_put(jnp.zeros((n * 4, d), jnp.float32), tok_sh)
    hlo = _hlo(fn, tok, jax.device_put(router, rep),
               jax.device_put(w1, ep_sh), jax.device_put(w2, ep_sh))
    n_a2a = _count(r"all-to-all(?:-start)?\(", hlo)
    assert 1 <= n_a2a <= 2, f"EP dispatch should be 1-2 all-to-alls, got {n_a2a}"


def test_flagship_spmd_step_contains_gradient_reduction():
    """The flagship transformer train step over (data=2, seq=2, tensor=2)
    compiles with collective ops present (the gradient psum the reference
    implements as NCCLAllreduce)."""
    import optax
    from horovod_tpu.models.transformer import (TransformerConfig,
                                                init_params, make_train_step,
                                                shard_params)
    devs = np.array(jax.devices()[:8]).reshape(2, 2, 2)
    mesh = Mesh(devs, ("data", "seq", "tensor"))
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                            d_ff=64, max_seq=16, dtype=jnp.float32)
    params = shard_params(init_params(jax.random.PRNGKey(0), cfg), mesh, cfg)
    opt = optax.sgd(0.01)
    step = make_train_step(mesh, cfg, opt)
    tok = jax.device_put(jnp.zeros((4, 16), jnp.int32),
                         NamedSharding(mesh, P("data", "seq")))
    opt_state = opt.init(params)
    hlo = step.lower(params, opt_state, tok, tok).compile().as_text()
    n_coll = (_count(r"all-reduce", hlo) + _count(r"reduce-scatter", hlo)
              + _count(r"all-gather", hlo) + _count(r"collective-permute", hlo))
    assert n_coll >= 3, f"expected gradient/activation collectives, got {n_coll}"


def test_fused_broadcast_is_one_collective_per_bucket():
    """grouped_broadcast's bucket program (r4): 40 packed leaves + the
    root-active flag -> the broadcastable data travels as ONE collective
    (the masked-psum broadcast of the packed buffer), with only the tiny
    flag as a second one — never one collective per leaf."""
    mesh = _world_mesh()
    shapes = tuple((5, 4) for _ in range(40))
    fn = C.build_fused_broadcast(mesh, "world", 0, shapes, jnp.float32)
    total = sum(int(np.prod(s)) for s in shapes)
    packed = jax.device_put(jnp.zeros((8, total), jnp.float32),
                            NamedSharding(mesh, P("world")))
    active = jax.device_put(jnp.ones((8, 1), jnp.int32),
                            NamedSharding(mesh, P("world")))
    hlo = _hlo(fn, packed, active)
    n_ar = _count(r"all-reduce(?:-start)?\(", hlo)
    assert n_ar <= 2, \
        f"expected <=2 collectives (packed data + flag), found {n_ar}"


def test_grouped_allreduce_single_launch_one_program():
    """VERDICT r4 weak #1 lever: the whole grouped allreduce — every
    bucket's pack, collective, and unpack — is ONE compiled program with
    exactly one all-reduce per bucket (2 here), so the eager step pays one
    dispatch instead of 2 per bucket."""
    mesh = _world_mesh()
    shapes = tuple((64,) for _ in range(6))
    buckets = [[0, 1, 2], [3, 4, 5]]
    fn = C.build_grouped_allreduce(mesh, "world", ReduceOp.SUM, shapes,
                                   [jnp.float32] * 6, buckets)
    args = [jax.device_put(jnp.zeros((8, 192), jnp.float32),
                           NamedSharding(mesh, P("world")))
            for _ in buckets]
    hlo = _hlo(fn, *args)
    n_ar = _count(r"all-reduce(?:-start)?\(", hlo)
    # at MOST one collective per bucket; XLA's all-reduce combiner may
    # merge small buckets further (fewer launches still upholds the
    # fusion-buffer guarantee — the bound is what bucketing promises)
    assert 1 <= n_ar <= 2, \
        f"expected <= one all-reduce per bucket (2), got {n_ar}"


def test_replay_step_lowers_to_single_fused_program():
    """Step-capture replay (core/replay.py): a captured step of many
    per-leaf allreduces is ONE compiled program — pack, one all-reduce per
    fusion bucket, unpack — so the whole steady-state step is a single
    dispatch (the ISSUE r5 acceptance bar)."""
    from jax.sharding import NamedSharding
    mesh = _world_mesh()
    shapes = tuple((7, 3) for _ in range(20))
    # one reduce segment, all 20 tensors in one bucket
    segments = (("reduce", int(ReduceOp.SUM), 1.0, 1.0, 0, shapes,
                 (tuple(range(20)),)),)
    fn = C.build_replay_step(mesh, "world", segments)
    rep = NamedSharding(mesh, P())
    args = [jax.device_put(jnp.ones(s, jnp.float32), rep) for s in shapes]
    hlo = _hlo(fn, *args)
    n_ar = _count(r"all-reduce(?:-start)?\(", hlo)
    assert n_ar == 1, f"expected ONE fused all-reduce, found {n_ar}"
    # and it computes the allreduce: every output = 8x its input here
    # (8 'ranks', each contributing the same replicated value)
    outs = fn(*args)
    np.testing.assert_allclose(np.asarray(outs[0]), 8.0 * np.ones((7, 3)),
                               rtol=1e-6)


def test_replay_step_multi_segment_bounded_collectives():
    """A mixed captured step (two reduce segments with different ops + a
    broadcast segment) still lowers to one program with at most one
    collective per bucket."""
    from jax.sharding import NamedSharding
    mesh = _world_mesh()
    segments = (
        ("reduce", int(ReduceOp.SUM), 1.0, 1.0, 0,
         ((16,), (16,)), ((0, 1),)),
        ("reduce", int(ReduceOp.MAX), 1.0, 1.0, 0, ((8,),), ((0,),)),
        ("bcast", 0, 1.0, 1.0, 0, ((4,),), ((0,),)),
    )
    fn = C.build_replay_step(mesh, "world", segments)
    rep = NamedSharding(mesh, P())
    args = [jax.device_put(jnp.ones(s, jnp.float32), rep)
            for s in ((16,), (16,), (8,), (4,))]
    hlo = _hlo(fn, *args)
    n_coll = (_count(r"all-reduce(?:-start)?\(", hlo)
              + _count(r"reduce-scatter", hlo))
    # sum bucket + max bucket + broadcast's masked psum = at most 3
    assert 1 <= n_coll <= 3, f"expected <=3 collectives, got {n_coll}"
    outs = fn(*args)
    np.testing.assert_allclose(np.asarray(outs[0]), 8.0 * np.ones((16,)))
    np.testing.assert_allclose(np.asarray(outs[2]), np.ones((8,)))  # MAX
    np.testing.assert_allclose(np.asarray(outs[3]), np.ones((4,)))  # bcast


def test_grouped_reducescatter_one_collective_per_bucket():
    """ZeRO-1 sync leg: the grouped reduce-scatter program must lower to
    exactly one reduce-scatter per fusion bucket (no stray allreduce), and
    the grouped allgather inverse must reconstruct the reduced values
    through exactly one all-gather per bucket — padding included (totals
    192 and 100 do not divide 8)."""
    mesh = _world_mesh()
    shapes = tuple((64,) for _ in range(3)) + ((25,), (75,))
    buckets = [[0, 1, 2], [3, 4]]
    rs = C.build_grouped_reducescatter(mesh, "world", ReduceOp.SUM, shapes,
                                       [jnp.float32] * 5, buckets)
    rng = np.random.RandomState(0)
    data = [rng.randn(8, 192).astype(np.float32),
            rng.randn(8, 100).astype(np.float32)]
    args = [jax.device_put(jnp.asarray(d), NamedSharding(mesh, P("world")))
            for d in data]
    hlo = _hlo(rs, *args)
    assert _count(r"reduce-scatter(?:-start)?\(", hlo) == 2, hlo[:400]
    assert _count(r"all-reduce(?:-start)?\(", hlo) == 0
    shards = rs(*args)
    ag = C.build_grouped_allgather(mesh, "world", shapes,
                                   [jnp.float32] * 5, buckets)
    hlo = _hlo(ag, *shards)
    assert _count(r"all-gather(?:-start)?\(", hlo) == 2
    assert _count(r"all-reduce(?:-start)?\(", hlo) == 0
    outs = ag(*shards)
    flat0 = data[0].sum(axis=0)
    for k in range(3):
        np.testing.assert_allclose(np.asarray(outs[k]),
                                   flat0[k * 64:(k + 1) * 64], rtol=1e-5)
    flat1 = data[1].sum(axis=0)
    np.testing.assert_allclose(np.asarray(outs[3]), flat1[:25], rtol=1e-5)
    np.testing.assert_allclose(np.asarray(outs[4]), flat1[25:], rtol=1e-5)


def test_sharded_replay_step_structure():
    """ISSUE 2 CI satellite: the sharded replay step — a captured ZeRO-1
    eager step — lowers to exactly one reduce-scatter and one all-gather
    per fusion bucket, with NO stray all-reduce (the fusion contract of
    the rs -> shard-update -> ag pipeline)."""
    from jax.sharding import NamedSharding
    mesh = _world_mesh()
    grad_shapes = tuple((7, 3) for _ in range(10)) + tuple((11,) for _ in range(4))
    n_grads = len(grad_shapes)
    # a momentum-style shard state leaf per bucket (2 buckets below) plus
    # the flat parameter master shards
    buckets = ((0, 1, 2, 3, 4, 5, 6, 7, 8, 9), (10, 11, 12, 13))
    totals = [210, 44]
    shard_sizes = [-(-t // 8) for t in totals]
    state_shapes = tuple((s,) for s in shard_sizes) * 2  # mu + master copy
    shapes = grad_shapes + state_shapes

    def update(shards, state):
        mu = state[:2]
        master = state[2:]
        new_mu = [0.9 * m + s for m, s in zip(mu, shards)]
        new_master = [p - 0.1 * m for p, m in zip(master, new_mu)]
        return list(new_master), new_mu + new_master

    segments = (("sharded", (int(ReduceOp.SUM), "upd", n_grads),
                 1.0, 1.0, 0, shapes, buckets),)
    fn = C.build_replay_step(mesh, "world", segments,
                             sharded_updates={"upd": update})
    rep = NamedSharding(mesh, P())
    args = [jax.device_put(jnp.ones(s, jnp.float32), rep) for s in shapes]
    hlo = _hlo(fn, *args)
    n_rs = _count(r"reduce-scatter(?:-start)?\(", hlo)
    n_ag = _count(r"all-gather(?:-start)?\(", hlo)
    n_ar = _count(r"all-reduce(?:-start)?\(", hlo)
    assert n_rs == 2, f"expected one reduce-scatter per bucket (2), got {n_rs}"
    assert n_ag == 2, f"expected one all-gather per bucket (2), got {n_ag}"
    assert n_ar == 0, f"expected NO stray all-reduce, got {n_ar}"
    # numerics on the replicated claim: 8 identical rank contributions sum
    # to 8; mu' = 0.9*1 + 8 = 8.9; master' = 1 - 0.1*8.9 = 0.11
    outs = fn(*args)
    np.testing.assert_allclose(np.asarray(outs[0]),
                               np.full((7, 3), 0.11), rtol=1e-5)
    # new mu state leaf (first state output) = 0.9*1 + 8
    np.testing.assert_allclose(np.asarray(outs[n_grads]),
                               np.full((shard_sizes[0],), 8.9), rtol=1e-6)


def test_reducescatter_builder_pads_odd_dim0():
    """Engine satellite: dim0=7 over 8 ranks — the builder pads to 8 rows
    inside the program; concatenating the per-rank shards (trimmed of the
    zero tail) reconstructs the full reduced tensor."""
    mesh = _world_mesh()
    fn = C.build_reducescatter(mesh, "world", ReduceOp.SUM, pad_rows=1)
    rng = np.random.RandomState(1)
    data = rng.randn(8, 7, 3).astype(np.float32)
    out = fn(jax.device_put(jnp.asarray(data),
                            NamedSharding(mesh, P("world"))))
    got = np.asarray(out)            # (8, 1, 3): one padded row per rank
    expect = data.sum(axis=0)
    np.testing.assert_allclose(got[:7, 0], expect, rtol=1e-5)
    np.testing.assert_allclose(got[7, 0], 0.0, atol=1e-6)


def test_grouped_allreduce_rejects_mixed_dtype_bucket():
    """The dtypes parameter now enforces the bucket_by_size contract
    (ADVICE r5): a hand-rolled mixed-dtype bucket fails loudly."""
    mesh = _world_mesh()
    with pytest.raises(ValueError, match="mixes dtypes"):
        C.build_grouped_allreduce(mesh, "world", ReduceOp.SUM,
                                  ((4,), (4,)), [jnp.float32, jnp.int32],
                                  [[0, 1]])


# -- ISSUE 6: bucket-pipelined overlap structure ----------------------------

_COLLECTIVE_PRIMS = {"psum", "reduce_scatter", "all_gather", "all_to_all",
                     "ppermute", "psum_scatter"}


def _shard_map_body(jaxpr):
    """The innermost sub-jaxpr holding the collective primitives (the
    shard_map manual region)."""
    for eqn in jaxpr.eqns:
        for v in eqn.params.values():
            jv = getattr(v, "jaxpr", v)
            if hasattr(jv, "eqns"):
                if eqn.primitive.name == "shard_map":
                    return jv
                body = _shard_map_body(jv)
                if body is not None:
                    return body
    return None


def _collective_interpose_violations(body):
    """IR-level serialization check (the ISSUE 6 acceptance bar): walk the
    manual region's eqns in trace order and report every NON-collective
    eqn that consumes (transitively) an earlier collective's output while
    at least one collective is still to be issued after it. In the serial
    PR 1 form, bucket i's unpack (dynamic_slice of the psum result) sits
    between reduce(i) and reduce(i+1) — exactly such a violation; the
    pipelined form must have none (collective-to-collective chains, e.g.
    the hierarchical RS->AG ladder, are the wire itself and are allowed).
    Returns (violations, n_collectives)."""
    tainted = set()       # vars derived from a collective output
    coll_pos = [i for i, e in enumerate(body.eqns)
                if e.primitive.name in _COLLECTIVE_PRIMS]
    if not coll_pos:
        return [], 0
    last = coll_pos[-1]
    violations = []
    for i, eqn in enumerate(body.eqns):
        is_coll = eqn.primitive.name in _COLLECTIVE_PRIMS
        consumes = any(getattr(v, "count", None) is not None and v in tainted
                       for v in eqn.invars)
        if consumes and not is_coll and i < last:
            violations.append((i, eqn.primitive.name))
        if is_coll or consumes:
            tainted.update(v for v in eqn.outvars)
    return violations, len(coll_pos)


def test_pipelined_replay_step_no_cross_bucket_dependency():
    """The pipelined replay step on the 8-device (2x4) CPU world: one
    collective per bucket, and NO non-collective op between two
    collectives consumes an earlier collective's result — i.e. bucket
    i+1's pack does not wait behind bucket i's reduce; the serialization
    PR 1 introduced is actually gone at the IR level. The serial builder
    is asserted to STILL have the interposing consumers, so this test
    distinguishes the two forms rather than passing vacuously."""
    mesh = _world_mesh()
    shapes = tuple((7, 3) for _ in range(9))
    buckets = ((0, 1, 2), (3, 4, 5), (6, 7, 8))
    segments = (("reduce", int(ReduceOp.SUM), 1.0, 1.0, 0, shapes,
                 buckets),)
    args = [jnp.ones(s, jnp.float32) for s in shapes]

    pipelined = C.build_replay_step(mesh, "world", segments, pipeline=True)
    body = _shard_map_body(jax.make_jaxpr(pipelined)(*args).jaxpr)
    assert body is not None
    violations, n_coll = _collective_interpose_violations(body)
    assert n_coll == len(buckets), \
        f"expected one collective per bucket ({len(buckets)}), got {n_coll}"
    assert not violations, \
        f"pipelined form still serializes at the IR level: {violations}"

    serial = C.build_replay_step(mesh, "world", segments, pipeline=False)
    sbody = _shard_map_body(jax.make_jaxpr(serial)(*args).jaxpr)
    sviol, _ = _collective_interpose_violations(sbody)
    assert sviol, ("the serial form no longer interposes unpacks between "
                   "bucket collectives — this test is vacuous, update it")

    # same values either way (8 identical 'rank' contributions -> x8)
    o0, o1 = serial(*args), pipelined(*args)
    for a, b in zip(o0, o1):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_allclose(np.asarray(o1[0]), 8.0 * np.ones((7, 3)),
                               rtol=1e-6)


def test_pipelined_sharded_replay_step_structure():
    """The pipelined SHARDED replay step: per-bucket reduce-scatter and
    all-gather stages with no stray all-reduce (the PR 2 bar holds under
    the new schedule), and no non-collective consumer interposing between
    collectives except the shard-local update itself — which is the one
    legitimate synchronization point (it needs every bucket's shard)."""
    mesh = _world_mesh()
    grad_shapes = tuple((6,) for _ in range(4))
    buckets = ((0, 1), (2, 3))
    shard_sizes = [-(-12 // 8)] * 2
    state_shapes = tuple((s,) for s in shard_sizes)
    shapes = grad_shapes + state_shapes

    def update(shards, state):
        new_mu = [0.9 * m + s for m, s in zip(state, shards)]
        return [s - 0.1 * m for s, m in zip(shards, new_mu)], new_mu

    segments = (("sharded", (int(ReduceOp.SUM), "upd", 4), 1.0, 1.0, 0,
                 shapes, buckets),)
    fn = C.build_replay_step(mesh, "world", segments,
                             sharded_updates={"upd": update},
                             pipeline=True)
    rep = NamedSharding(mesh, P())
    args = [jax.device_put(jnp.ones(s, jnp.float32), rep) for s in shapes]
    hlo = _hlo(fn, *args)
    assert _count(r"reduce-scatter(?:-start)?\(", hlo) == 2
    assert _count(r"all-gather(?:-start)?\(", hlo) == 2
    assert _count(r"all-reduce(?:-start)?\(", hlo) == 0
    # trace order: both reduce-scatters issue before ANY all-gather (the
    # rs(i+1)-behind-ag(i) serialization is gone)
    body = _shard_map_body(jax.make_jaxpr(fn)(*args).jaxpr)
    names = [e.primitive.name for e in body.eqns
             if e.primitive.name in _COLLECTIVE_PRIMS]
    assert names == ["reduce_scatter", "reduce_scatter",
                     "all_gather", "all_gather"], names


def test_split_sharded_update_has_no_allgather():
    """The prefetch split (ISSUE 6 tentpole): the rs->update stage program
    contains the per-bucket reduce-scatters and NO all-gather — the
    gather rides the separate prefetch leg
    (build_grouped_allgather), whose program contains only the per-bucket
    all-gathers. Combined they reproduce the fused step exactly."""
    mesh = _world_mesh()
    grad_shapes = tuple((6,) for _ in range(4))
    buckets = [[0, 1], [2, 3]]
    st_shapes = ((2,), (2,))

    def update(shards, state):
        return [s + m for s, m in zip(shards, state)], list(state)

    upd = C.build_sharded_update(mesh, "world", ReduceOp.SUM, grad_shapes,
                                 [jnp.float32] * 4, buckets, st_shapes,
                                 None, update, packed=True)
    ag = C.build_grouped_allgather(mesh, "world", grad_shapes,
                                   [jnp.float32] * 4, buckets,
                                   pipeline=True)
    fused = C.build_sharded_step(mesh, "world", ReduceOp.SUM, grad_shapes,
                                 [jnp.float32] * 4, buckets, st_shapes,
                                 None, update, pipeline=True)
    rng = np.random.RandomState(3)
    packed = [jax.device_put(
        jnp.asarray(rng.randn(8, 12).astype(np.float32)),
        NamedSharding(mesh, P("world"))) for _ in buckets]
    state = [jax.device_put(jnp.ones((2,), jnp.float32),
                            NamedSharding(mesh, P())) for _ in range(2)]
    hlo_upd = _hlo(upd, *packed, *state)
    assert _count(r"reduce-scatter(?:-start)?\(", hlo_upd) == 2
    assert _count(r"all-gather(?:-start)?\(", hlo_upd) == 0
    assert _count(r"all-reduce(?:-start)?\(", hlo_upd) == 0
    shards = upd(*packed, *state)
    hlo_ag = _hlo(ag, *shards[:2])
    assert _count(r"all-gather(?:-start)?\(", hlo_ag) == 2
    assert _count(r"reduce-scatter(?:-start)?\(", hlo_ag) == 0
    split_params = ag(*shards[:2])
    fused_outs = fused(*packed, *state)
    for a, b in zip(fused_outs[:4], split_params):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(fused_outs[4:], shards[2:]):
        np.testing.assert_array_equal(
            np.asarray(a.addressable_shards[0].data),
            np.asarray(b.addressable_shards[0].data))


# -- ISSUE 10: topology-aware algorithm selection ---------------------------

_PAIR_GROUPS = r"replica_groups=\{\{0,1\},\{2,3\},\{4,5\},\{6,7\}\}"
_NODE_GROUPS = r"replica_groups=\{\{0,1,2,3\},\{4,5,6,7\}\}"


def _topo84():
    from horovod_tpu.parallel.mesh import Topology
    return Topology(size=8, local_size=4, platform="tpu", source="override")


def test_auto_selection_lowers_tree_and_hierarchical_per_bucket():
    """The ISSUE 10 acceptance bar: on an 8-device 2-slice topology,
    ``auto`` lowers a small latency-bound bucket to the TREE form
    (log2(8)=3 chained pair-group all-reduces) and a large bucket to the
    hierarchical RS/AG ladder with node-local replica groups — in ONE
    grouped program. Forcing ``flat`` collapses both buckets to plain
    whole-world all-reduces with neither group structure, so the test
    distinguishes the selections rather than passing vacuously."""
    topo = _topo84()
    small_elems, large_elems = 1024, 256 * 1024      # 4 KB vs 1 MB fp32
    shapes = ((small_elems,), (large_elems,))
    buckets = [[0], [1]]
    algos = tuple(
        C.choose_algorithm("allreduce", 4 * e, topo)
        for e in (small_elems, large_elems))
    assert algos == ("tree", "hierarchical"), algos
    mesh = _world_mesh()
    args = [jax.device_put(jnp.ones((8, e), jnp.float32),
                           NamedSharding(mesh, P("world")))
            for e in (small_elems, large_elems)]

    auto_fn = C.build_grouped_allreduce(
        mesh, "world", ReduceOp.SUM, shapes, [jnp.float32] * 2, buckets,
        local_size=topo.local_size, algos=algos)
    hlo = _hlo(auto_fn, *args).replace(" ", "")
    # tree bucket: exactly 3 chained pair-group psums (dependent rounds
    # the combiner cannot merge)
    assert _count(r"all-reduce(?:-start)?\(", hlo) == 3, hlo[:400]
    assert re.search(_PAIR_GROUPS, hlo), "tree pair groups missing"
    # hierarchical bucket: the RS/AG ladder over node-local groups
    assert re.search(_NODE_GROUPS, hlo), "node-local ladder groups missing"
    assert (_count(r"reduce-scatter", hlo) >= 1
            or _count(r"all-gather", hlo) >= 1)

    flat_fn = C.build_grouped_allreduce(
        mesh, "world", ReduceOp.SUM, shapes, [jnp.float32] * 2, buckets,
        local_size=topo.local_size, algos=("flat", "flat"))
    fhlo = _hlo(flat_fn, *args).replace(" ", "")
    n_ar = _count(r"all-reduce(?:-start)?\(", fhlo)
    assert 1 <= n_ar <= 2, f"flat should be whole-world all-reduce: {n_ar}"
    assert not re.search(_PAIR_GROUPS, fhlo)
    assert not re.search(_NODE_GROUPS, fhlo)
    assert _count(r"reduce-scatter", fhlo) == 0

    # same numbers either way (8 identical 'rank' contributions -> x8)
    for a, b in zip(auto_fn(*args), flat_fn(*args)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5)


def test_tree_allreduce_builder_structure_and_values():
    mesh = _world_mesh()
    fn = C.build_tree_allreduce(mesh, "world", ReduceOp.SUM)
    x = jax.device_put(jnp.arange(8 * 6, dtype=jnp.float32).reshape(8, 6),
                       NamedSharding(mesh, P("world")))
    hlo = _hlo(fn, x).replace(" ", "")
    assert _count(r"all-reduce(?:-start)?\(", hlo) == 3
    assert re.search(_PAIR_GROUPS, hlo)
    out = np.asarray(fn(x))
    np.testing.assert_allclose(
        out, np.arange(8 * 6, dtype=np.float32).reshape(8, 6).sum(0))


def test_replay_step_per_bucket_algo_segments():
    """The replay segment's topology field carries per-bucket algorithms
    (the (local_size, algos) tuple form): the armed program lowers its
    small bucket to the tree and its large bucket to the ladder — so
    warmup and steady state resolve the same topology-aware schedule."""
    from jax.sharding import NamedSharding
    mesh = _world_mesh()
    shapes = ((64,), (4096,))
    segments = (("reduce", int(ReduceOp.SUM), 1.0, 1.0,
                 (4, ("tree", "hierarchical")), shapes, ((0,), (1,))),)
    fn = C.build_replay_step(mesh, "world", segments, pipeline=True)
    rep = NamedSharding(mesh, P())
    args = [jax.device_put(jnp.ones(s, jnp.float32), rep) for s in shapes]
    hlo = _hlo(fn, *args).replace(" ", "")
    assert _count(r"all-reduce(?:-start)?\(", hlo) == 3  # the tree rounds
    assert re.search(_PAIR_GROUPS, hlo)
    assert re.search(_NODE_GROUPS, hlo)
    outs = fn(*args)
    np.testing.assert_allclose(np.asarray(outs[0]), 8.0 * np.ones((64,)))
    np.testing.assert_allclose(np.asarray(outs[1]), 8.0 * np.ones((4096,)))
    # legacy int field still means "one algorithm everywhere" (flat here)
    legacy = C.build_replay_step(
        mesh, "world",
        (("reduce", int(ReduceOp.SUM), 1.0, 1.0, 0, shapes,
          ((0,), (1,))),))
    lhlo = _hlo(legacy, *args).replace(" ", "")
    assert not re.search(_PAIR_GROUPS, lhlo)
    assert not re.search(_NODE_GROUPS, lhlo)


def test_sharded_step_hierarchical_ag_leg():
    """ZeRO-1 with a hierarchical return all-gather: the reduce-scatter
    leg stays the flat whole-world scatter (shard-ownership invariant)
    while the gather lowers to the two-level ladder — and the result is
    bitwise-identical to the flat-gather program."""
    mesh = _world_mesh()
    grad_shapes = tuple((6,) for _ in range(4))
    buckets = [[0, 1], [2, 3]]
    st_shapes = ((2,), (2,))

    def update(shards, state):
        return [s + m for s, m in zip(shards, state)], list(state)

    kw = dict(pipeline=True)
    hier = C.build_sharded_step(mesh, "world", ReduceOp.SUM, grad_shapes,
                                [jnp.float32] * 4, buckets, st_shapes,
                                None, update, local_size=4,
                                ag_algos=("hierarchical", "hierarchical"),
                                **kw)
    flat = C.build_sharded_step(mesh, "world", ReduceOp.SUM, grad_shapes,
                                [jnp.float32] * 4, buckets, st_shapes,
                                None, update, **kw)
    rng = np.random.RandomState(5)
    packed = [jax.device_put(
        jnp.asarray(rng.randn(8, 12).astype(np.float32)),
        NamedSharding(mesh, P("world"))) for _ in buckets]
    state = [jax.device_put(jnp.ones((2,), jnp.float32),
                            NamedSharding(mesh, P())) for _ in range(2)]
    hhlo = _hlo(hier, *packed, *state).replace(" ", "")
    # whole-world scatters survive; gathers go node-local two-level
    assert _count(r"reduce-scatter(?:-start)?\(", hhlo) >= 2
    assert re.search(_NODE_GROUPS, hhlo), "no two-level gather groups"
    for a, b in zip(hier(*packed, *state), flat(*packed, *state)):
        np.testing.assert_array_equal(
            np.asarray(a.addressable_shards[0].data),
            np.asarray(b.addressable_shards[0].data))


def test_grouped_allreduce_hierarchical_ladder():
    """The single-launch grouped program with local_size=4 must lower each
    bucket's reduction to the hierarchical RS/AG ladder with node-local
    replica groups — the same structural bar the per-bucket fused program
    meets — AND produce numerically correct sums."""
    import re
    mesh = _world_mesh()
    shapes = tuple((32,) for _ in range(4))
    buckets = [[0, 1], [2, 3]]
    fn = C.build_grouped_allreduce(mesh, "world", ReduceOp.SUM, shapes,
                                   [jnp.float32] * 4, buckets,
                                   local_size=4)
    rng = np.random.RandomState(0)
    data = [rng.randn(8, 64).astype(np.float32) for _ in buckets]
    args = [jax.device_put(jnp.asarray(d),
                           NamedSharding(mesh, P("world")))
            for d in data]
    hlo = _hlo(fn, *args)
    local_groups = re.search(r"replica_groups=\{\{0,1,2,3\},\{4,5,6,7\}\}",
                             hlo.replace(" ", ""))
    assert local_groups, "no node-local replica groups in grouped ladder"
    outs = fn(*args)
    for b, idxs in enumerate(buckets):
        expect = data[b].sum(axis=0)
        np.testing.assert_allclose(np.asarray(outs[idxs[0]]), expect[:32],
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(outs[idxs[1]]), expect[32:],
                                   rtol=1e-5, atol=1e-5)


def test_hierarchical_alltoall_lowers_to_two_level_exchange():
    """ISSUE 17 acceptance (IR structure): the two-phase alltoall on the
    8-device world with local_size=4 must lower to exactly TWO
    all-to-alls — one over the intra-slice (ICI) groups {0-3}/{4-7}, one
    over the cross-slice (DCN) column groups {0,4}/{1,5}/... — while the
    forced-flat program collapses to ONE whole-world all-to-all; both
    routings are pure chunk moves, so the outputs are bitwise-equal."""
    mesh = _world_mesh()
    hfn = C.build_hierarchical_alltoall(mesh, "world", 4)
    ffn = C.build_alltoall(mesh, "world")
    x = jax.device_put(
        jnp.arange(8 * 16 * 3, dtype=jnp.float32).reshape(8, 16, 3),
        NamedSharding(mesh, P("world")))
    hhlo = _hlo(hfn, x)
    fhlo = _hlo(ffn, x)
    assert _count(r"all-to-all(?:-start)?\(", hhlo) == 2, \
        "two-phase program did not lower to exactly two exchanges"
    assert _count(r"all-to-all(?:-start)?\(", fhlo) == 1, \
        "flat program is not one whole-world exchange"
    hflat = hhlo.replace(" ", "")
    assert re.search(r"replica_groups=\{\{0,1,2,3\},\{4,5,6,7\}\}", hflat), \
        "no intra-slice (ICI) replica groups in the two-phase HLO"
    assert re.search(r"replica_groups=\{\{0,4\},\{1,5\},\{2,6\},\{3,7\}\}",
                     hflat), \
        "no cross-slice (DCN) replica groups in the two-phase HLO"
    assert re.search(r"replica_groups=\{\{0,1,2,3,4,5,6,7\}\}",
                     fhlo.replace(" ", "")), \
        "flat exchange is not whole-world"
    np.testing.assert_array_equal(
        np.asarray(jax.block_until_ready(hfn(x))),
        np.asarray(jax.block_until_ready(ffn(x))))


def test_grouped_alltoall_per_bucket_algos_structure():
    """Per-bucket alltoall selection in ONE grouped program: a flat
    bucket contributes one whole-world all-to-all, a hierarchical bucket
    two sliced ones — three exchanges total, numerics identical to
    all-flat."""
    mesh = _world_mesh()
    shapes = ((16, 4), (24, 4))
    dtypes = [jnp.float32] * 2
    buckets = [[0], [1]]
    mixed = C.build_grouped_alltoall(
        mesh, "world", shapes, dtypes, buckets, local_size=4,
        algos=(C.ALGO_FLAT, C.ALGO_HIERARCHICAL))
    flat = C.build_grouped_alltoall(
        mesh, "world", shapes, dtypes, buckets, local_size=4,
        algos=(C.ALGO_FLAT, C.ALGO_FLAT))
    rng = np.random.RandomState(0)
    args = [jax.device_put(
        jnp.asarray(rng.randn(8, *s).astype(np.float32)),
        NamedSharding(mesh, P("world"))) for s in shapes]
    hlo = _hlo(mixed, *args)
    assert _count(r"all-to-all(?:-start)?\(", hlo) == 3, \
        "expected 1 flat + 2 hierarchical-phase exchanges"
    for a, b in zip(mixed(*args), flat(*args)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_reducescatter_selection_stays_flat():
    """The ISSUE 17 selection surface is alltoall-only on the scatter
    side: reducescatter never takes the hierarchical ladder (auto OR
    forced — forcing demotes), even on a fabric where allreduce and
    alltoall both would."""
    from horovod_tpu.parallel.mesh import Topology
    topo = Topology(size=8, local_size=4, platform="tpu", source="test")
    nbytes = 32 * 1024 ** 2
    assert C.choose_algorithm("allreduce", nbytes, topo,
                              tree_threshold_bytes=0) == \
        C.ALGO_HIERARCHICAL
    assert C.choose_algorithm("alltoall", nbytes, topo,
                              tree_threshold_bytes=0) == \
        C.ALGO_HIERARCHICAL
    assert C.choose_algorithm("reducescatter", nbytes, topo,
                              tree_threshold_bytes=0) == C.ALGO_FLAT
    assert C.validate_algorithm("reducescatter", C.ALGO_HIERARCHICAL,
                                8, 4) == C.ALGO_FLAT
