"""Schedule parity + structure suite for the ISSUE 16 pipeline schedules.

The load-bearing claim: interleaved virtual-stage and zero-bubble (B/W
split) schedules are BITWISE identical to 1F1B at matched microbatch
count — same loss, same gradients, same trajectory — because they reorder
when each microbatch's F/B/W work runs, never what it computes, and every
gradient accumulator is added in microbatch order. The suite drives every
schedule against 1F1B on a real 4-stage CPU mesh, including a
non-divisible microbatch count and the m < stages degenerate case (demote
to 1F1B with a one-time WARNING, not a crash), plus the compiled-structure
half of the tentpole: a replayed steady-state PP x DP(engine) step is O(1)
host dispatches regardless of microbatch count.
"""

import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from horovod_tpu.parallel.pipeline import (
    PIPELINE_SCHEDULES, build_schedule_tables, pipeline_bubble_fraction,
    pipeline_chunk_placement, pipeline_train_step, predict_schedule_bubble,
    predict_schedule_time, resolve_pipeline_schedule, split_microbatches)

S = 4          # stages
NC = 8         # total cells (2 per stage; 1 per chunk at v=2)
D = 16
BM = 6         # rows per microbatch


def _cells(seed=0):
    rng = np.random.RandomState(seed)
    return {"w": jnp.asarray(rng.randn(NC, D, D), jnp.float32) * 0.3,
            "b": jnp.asarray(rng.randn(NC, D), jnp.float32) * 0.1}


def _cell(p, h):
    return jnp.tanh(h @ p["w"] + p["b"])


def _stage_fn(sp, x):
    h, _ = lax.scan(lambda h, lp: (_cell(lp, h), None), x, sp)
    return h


def _loss(y, tgt):
    return jnp.mean((y - tgt) ** 2)


def _run(schedule, n_virtual, n_micro, steps=2, seed=0):
    """Run `steps` SGD steps of the 8-cell pipeline under `schedule`;
    return (losses, final params in MODEL order) for bitwise comparison."""
    mesh = Mesh(np.array(jax.devices()[:S]), ("pipe",))
    sched, v = resolve_pipeline_schedule(schedule, S, n_micro, n_virtual)
    lpc = NC // (S * v)
    if pipeline_chunk_placement(sched, v) == "roundrobin":
        order = np.concatenate([
            np.arange((j * S + s) * lpc, (j * S + s + 1) * lpc)
            for s in range(S) for j in range(v)])
    else:
        order = np.arange(NC)
    params = jax.device_put(
        {k: np.asarray(a)[order] for k, a in _cells(seed).items()},
        NamedSharding(mesh, P("pipe")))

    def body(params, micro_in, micro_tgt):
        sp = params
        if v > 1:
            sp = jax.tree_util.tree_map(
                lambda a: a.reshape((v, lpc) + a.shape[1:]), params)
        loss, gs, _, _ = pipeline_train_step(
            _stage_fn, sp, micro_in, micro_tgt, _loss, "pipe", S,
            schedule=sched, n_virtual=v)
        if v > 1:
            gs = jax.tree_util.tree_map(
                lambda a: a.reshape((v * lpc,) + a.shape[2:]), gs)
        return loss, gs

    fn = jax.jit(jax.shard_map(body, mesh=mesh,
                           in_specs=(P("pipe"), P(), P()),
                           out_specs=(P(), P("pipe")), check_vma=False))
    rng = np.random.RandomState(100 + seed)
    x = split_microbatches(
        jnp.asarray(rng.randn(n_micro * BM, D), jnp.float32), n_micro)
    t = split_microbatches(
        jnp.asarray(rng.randn(n_micro * BM, D), jnp.float32), n_micro)
    losses = []
    for _ in range(steps):
        loss, gs = fn(params, x, t)
        losses.append(float(loss))
        params = jax.tree_util.tree_map(lambda p, g: p - 0.1 * g,
                                        params, gs)
    inv = np.argsort(order)
    final = {k: np.asarray(a)[inv] for k, a in params.items()}
    return losses, final


def _assert_bitwise(a, b):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x),
                                                   np.asarray(y)), a, b)


# ---------------------------------------------------------------------------
# bitwise trajectory parity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("schedule,n_virtual", [("zb", 1),
                                                ("interleaved", 2)])
def test_schedule_bitwise_parity(schedule, n_virtual):
    """zb / interleaved reproduce the 1F1B loss AND parameter trajectory
    bitwise over multiple steps at matched microbatch count."""
    base_l, base_p = _run("1f1b", 1, n_micro=8)
    l, p = _run(schedule, n_virtual, n_micro=8)
    assert l == base_l
    _assert_bitwise(p, base_p)


@pytest.mark.parametrize("schedule,n_virtual", [("zb", 1),
                                                ("interleaved", 2)])
def test_schedule_parity_non_divisible_micro(schedule, n_virtual):
    """m=5 is not divisible by 4 stages: the steady phase is ragged, every
    table row still fires each job exactly once, parity holds bitwise."""
    base_l, base_p = _run("1f1b", 1, n_micro=5)
    l, p = _run(schedule, n_virtual, n_micro=5)
    assert l == base_l
    _assert_bitwise(p, base_p)


def test_m_less_than_stages_demotes_once_with_warning():
    """m < stages demotes any schedule to 1F1B with a ONE-TIME RuntimeWarning
    (not a crash), and the demoted run is exactly the 1F1B run."""
    from horovod_tpu.parallel import pipeline as pl
    key = ("micro", "zb", S, 2)
    pl._DEMOTE_WARNED.discard(key)
    with pytest.warns(RuntimeWarning, match="no steady phase"):
        sched, v = resolve_pipeline_schedule("zb", S, 2, 1)
    assert (sched, v) == ("1f1b", 1)
    # second resolution of the same degenerate case is silent
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        sched2, _ = resolve_pipeline_schedule("zb", S, 2, 1)
    assert sched2 == "1f1b"
    base_l, base_p = _run("1f1b", 1, n_micro=2)
    l, p = _run("zb", 1, n_micro=2)
    assert l == base_l
    _assert_bitwise(p, base_p)


def test_unknown_schedule_demotes():
    from horovod_tpu.parallel import pipeline as pl
    pl._DEMOTE_WARNED.discard(("schedule", "wavefront"))
    with pytest.warns(RuntimeWarning, match="unknown pipeline schedule"):
        sched, _ = resolve_pipeline_schedule("wavefront", S, 8, 1)
    assert sched == "1f1b"


def test_auto_resolves_to_valid_schedule():
    sched, v = resolve_pipeline_schedule("auto", S, 8, 2)
    assert sched in PIPELINE_SCHEDULES and sched != "auto"
    # auto at m < stages must land on 1f1b (the only correct candidate)
    sched_low, _ = resolve_pipeline_schedule("auto", S, 2, 1)
    assert sched_low == "1f1b"


# ---------------------------------------------------------------------------
# flagship transformer parity
# ---------------------------------------------------------------------------

def test_flagship_zb_matches_1f1b():
    """The transformer flagship under schedule='zb' reproduces the 1F1B
    step bitwise (loss + updated params), embedding/head roles included."""
    import optax
    from horovod_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                                n_layers=4, d_ff=64, max_seq=16,
                                dtype=jnp.float32, attention="flash")
    params = tfm.init_params(jax.random.PRNGKey(3), cfg)
    rng = np.random.RandomState(7)
    tok = jnp.asarray(rng.randint(0, 64, size=(8, 16)).astype(np.int32))
    tgt = jnp.asarray(rng.randint(0, 64, size=(8, 16)).astype(np.int32))
    mesh = Mesh(np.array(jax.devices()[:4]), (tfm.PIPE_AXIS,))
    specs = tfm.pp_param_specs(cfg)

    def place():
        return jax.tree_util.tree_map(
            lambda x, s: jax.device_put(np.asarray(x),
                                        NamedSharding(mesh, s)),
            params, specs)

    outs = {}
    for sched in ("1f1b", "zb"):
        p = place()
        opt = optax.sgd(0.1)
        step = tfm.make_pp_train_step(mesh, cfg, opt, n_micro=4,
                                      schedule=sched)
        p, _, loss = step(p, opt.init(p), tok, tgt)
        outs[sched] = (float(loss), jax.tree_util.tree_map(np.asarray, p))
    assert outs["zb"][0] == outs["1f1b"][0]
    _assert_bitwise(outs["zb"][1], outs["1f1b"][1])


# ---------------------------------------------------------------------------
# predictor + table structure
# ---------------------------------------------------------------------------

def test_bubble_fraction_closed_forms():
    assert pipeline_bubble_fraction(4, 4) == pytest.approx(3 / 7)
    assert pipeline_bubble_fraction(4, 8, "1f1b") == pytest.approx(3 / 11)
    # interleaved: q/(m+q), q=(p-1)/v
    q = 3 / 2
    assert pipeline_bubble_fraction(4, 8, "interleaved", 2) \
        == pytest.approx(q / (8 + q))
    # one stage pipelines nothing
    assert pipeline_bubble_fraction(1, 8, "zb") == 0.0


def test_predictor_orders_schedules():
    """The analytic predictor ranks zb < interleaved < 1f1b on bubble at
    (p=4, m=8) — the ordering the paper's schedules exist to deliver."""
    b = {s: predict_schedule_bubble(s, 4, 8, v)
         for s, v in (("1f1b", 1), ("interleaved", 2), ("zb", 1))}
    assert b["zb"] < b["interleaved"] < b["1f1b"]
    # predictor time is positive and increases with m
    assert 0 < predict_schedule_time("zb", 4, 4) \
        < predict_schedule_time("zb", 4, 8)


@pytest.mark.parametrize("schedule,v,m", [("1f1b", 1, 8), ("zb", 1, 8),
                                          ("interleaved", 2, 8),
                                          ("zb", 1, 5),
                                          ("interleaved", 2, 5)])
def test_tables_fire_every_job_exactly_once(schedule, v, m):
    """Structural invariant: each (microbatch, chunk) F and B fires exactly
    once across the table, and under zb the W count equals the B count
    (every deferred weight pass lands)."""
    tb = build_schedule_tables(schedule, S, m, v)
    C = S * v
    f_seen, b_seen, w_seen = set(), set(), set()
    for tick in range(tb.ticks):
        for s in range(S):
            if tb.rows["f_active"][tick, s]:
                job = (int(tb.rows["f_m"][tick, s]),
                       int(tb.rows["f_j"][tick, s]), s)
                assert job not in f_seen
                f_seen.add(job)
            if tb.rows["b_active"][tick, s]:
                job = (int(tb.rows["b_m"][tick, s]),
                       int(tb.rows["b_j"][tick, s]), s)
                assert job not in b_seen
                b_seen.add(job)
            if tb.split_bw and tb.rows["w_active"][tick, s]:
                job = (int(tb.rows["w_m"][tick, s]),
                       int(tb.rows["w_j"][tick, s]), s)
                assert job not in w_seen
                w_seen.add(job)
    # every chunk's B fires for every microbatch
    assert len(b_seen) == m * C
    # F jobs exist for all but the last chunk (its F folds into B)
    assert len(f_seen) == m * (C - 1)
    if tb.split_bw:
        assert len(w_seen) == len(b_seen)


def test_1f1b_tick_count_matches_hand_schedule():
    """The greedy generator reproduces the canonical 1F1B tick count
    m + 2(p-1) — the hand-derived mapping pipeline_train_1f1b runs."""
    for m in (4, 5, 8, 12):
        assert build_schedule_tables("1f1b", S, m, 1).ticks == m + 2 * (S - 1)


def test_chunk_placement_rules():
    assert pipeline_chunk_placement("1f1b", 1) == "contiguous"
    assert pipeline_chunk_placement("1f1b", 2) == "contiguous"
    assert pipeline_chunk_placement("interleaved", 2) == "roundrobin"
    # at v=1 (one chunk per stage) the placements coincide
    assert pipeline_chunk_placement("zb", 1) == "contiguous"
    assert pipeline_chunk_placement("zb", 2) == "roundrobin"


# ---------------------------------------------------------------------------
# O(1) dispatches: PP x DP(engine) with replay
# ---------------------------------------------------------------------------

def test_replayed_pipeline_step_is_o1_dispatches():
    """Steady-state engine dispatches per PP x DP step are O(1) in the
    microbatch count: the microbatch loop lives inside ONE jitted scan, and
    the engine's DP-sync + ZeRO-1 update replays as one fused launch."""
    import optax
    import horovod_tpu as hvd
    from horovod_tpu.models import transformer as tfm
    from horovod_tpu.optimizer import DistributedEagerOptimizer

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                                n_layers=4, d_ff=64, max_seq=16,
                                dtype=jnp.float32, attention="flash")
    mesh = Mesh(np.array(jax.devices()[:4]), (tfm.PIPE_AXIS,))
    specs = tfm.pp_param_specs(cfg)
    rng = np.random.RandomState(5)
    tok = jnp.asarray(rng.randint(0, 64, size=(8, 16)).astype(np.int32))
    tgt = jnp.asarray(rng.randint(0, 64, size=(8, 16)).astype(np.int32))
    hvd.init()
    eng = hvd._engine()

    def steady_dispatches(n_micro):
        params = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(
                np.asarray(x), NamedSharding(mesh, s)),
            tfm.init_params(jax.random.PRNGKey(4), cfg), specs)
        opt = DistributedEagerOptimizer(optax.sgd(0.05), sharded=True,
                                        op=hvd.Sum)
        st = opt.init(params)
        step = tfm.make_pp_engine_train_step(mesh, cfg, opt, n_micro,
                                             schedule="zb")
        warmup = eng.config.step_replay_warmup + 2
        for _ in range(warmup):
            params, st, loss = step(params, st, tok, tgt)
        jax.block_until_ready(loss)
        d0 = eng.dispatch_count
        params, st, loss = step(params, st, tok, tgt)
        jax.block_until_ready(loss)
        return eng.dispatch_count - d0

    d4 = steady_dispatches(4)
    d8 = steady_dispatches(8)
    # O(1): doubling the microbatch count must not change the engine
    # dispatch count, and the replayed stream is a single fused launch
    assert d4 == d8 == 1
    assert eng.replay.replayed_steps > 0


# ---------------------------------------------------------------------------
# tier-1-safe perf smoke (CI: lint workflow runs -m perf)
# ---------------------------------------------------------------------------

@pytest.mark.perf
def test_pipeline_schedule_smoke_2stage():
    """2-stage tiny-model smoke: the schedule selector fires (env-style
    selector input resolved through resolve_pipeline_schedule) and replay
    capture arms on the engine-ridden step. Build + a few iterations on
    CPU, no timing assertions."""
    import optax
    import horovod_tpu as hvd
    from horovod_tpu.models import transformer as tfm
    from horovod_tpu.optimizer import DistributedEagerOptimizer

    sched, v = resolve_pipeline_schedule("zb", 2, 4, 1)
    assert sched == "zb" and v == 1     # selector fired, no demotion
    cfg = tfm.TransformerConfig(vocab_size=32, d_model=16, n_heads=2,
                                n_layers=2, d_ff=32, max_seq=8,
                                dtype=jnp.float32, attention="flash")
    mesh = Mesh(np.array(jax.devices()[:2]), (tfm.PIPE_AXIS,))
    specs = tfm.pp_param_specs(cfg)
    rng = np.random.RandomState(9)
    tok = jnp.asarray(rng.randint(0, 32, size=(4, 8)).astype(np.int32))
    tgt = jnp.asarray(rng.randint(0, 32, size=(4, 8)).astype(np.int32))
    hvd.init()
    eng = hvd._engine()
    params = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(np.asarray(x), NamedSharding(mesh, s)),
        tfm.init_params(jax.random.PRNGKey(8), cfg), specs)
    opt = DistributedEagerOptimizer(optax.sgd(0.05), sharded=True,
                                    op=hvd.Sum)
    st = opt.init(params)
    step = tfm.make_pp_engine_train_step(mesh, cfg, opt, n_micro=4,
                                         schedule=sched)
    captured0 = eng.replay.captured_streams
    for _ in range(eng.config.step_replay_warmup + 2):
        params, st, loss = step(params, st, tok, tgt)
    jax.block_until_ready(loss)
    assert np.isfinite(float(loss))
    assert eng.replay.captured_streams > captured0   # replay capture fired


def test_pp_moe_composition_2stage_2expert_bitwise_reproducible():
    """ISSUE 17 satellite: PP x MoE-EP composition — the 2-stage pipeline
    flagship with the MoE FFN (2 experts per stage-local layer) trains
    through make_pp_engine_train_step, loss improves, and the whole loss
    trajectory is bitwise-reproducible from identical state (fresh
    replay both runs, so capture/arm/replay paths line up too)."""
    import optax
    import horovod_tpu as hvd
    from horovod_tpu.models import transformer as tfm
    from horovod_tpu.optimizer import DistributedEagerOptimizer

    cfg = tfm.TransformerConfig(vocab_size=32, d_model=16, n_heads=2,
                                n_layers=2, d_ff=32, max_seq=8,
                                dtype=jnp.float32, attention="flash",
                                use_moe=True, n_experts=2,
                                moe_capacity_factor=2.0)
    mesh = Mesh(np.array(jax.devices()[:2]), (tfm.PIPE_AXIS,))
    specs = tfm.pp_param_specs(cfg)
    assert "router" in specs["layers"], "MoE pp specs must place the router"
    rng = np.random.RandomState(11)
    tok = jnp.asarray(rng.randint(0, 32, size=(4, 8)).astype(np.int32))
    tgt = jnp.asarray(rng.randint(0, 32, size=(4, 8)).astype(np.int32))
    hvd.init()
    eng = hvd._engine()

    def trajectory():
        eng.replay.invalidate_all("test isolation")
        params = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(
                np.asarray(x), NamedSharding(mesh, s)),
            tfm.init_params(jax.random.PRNGKey(8), cfg), specs)
        opt = DistributedEagerOptimizer(optax.sgd(0.05), sharded=True,
                                        op=hvd.Sum)
        st = opt.init(params)
        step = tfm.make_pp_engine_train_step(mesh, cfg, opt, n_micro=4,
                                             schedule="1f1b")
        out = []
        for _ in range(4):
            params, st, loss = step(params, st, tok, tgt)
            out.append(float(loss))
        return out

    l1 = trajectory()
    l2 = trajectory()
    assert l1 == l2, "PP x MoE trajectory must be bitwise-reproducible"
    assert all(np.isfinite(v) for v in l1)
    assert l1[-1] < l1[0], l1
