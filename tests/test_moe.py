"""Expert-parallel MoE tests: the EP-sharded layer (all-to-all dispatch over
4 expert shards) must match the single-shard reference bit-for-bit given the
same expert weights, gradients must flow, and capacity overflow must drop
tokens to zero (Switch semantics)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.parallel.moe import MoEParams, init_moe, moe_layer_p

E, D, F = 8, 16, 32
N_SHARD = 4


def _mesh(n=N_SHARD):
    return jax.sharding.Mesh(np.array(jax.devices()[:n]), ("expert",))


def _params(seed=0):
    """Full (unsharded) params with E experts."""
    return init_moe(jax.random.PRNGKey(seed), D, F, E, n_expert_shards=1)


def _shard_params(full: MoEParams, n=N_SHARD):
    e_local = E // n
    return [MoEParams(full.router,
                      full.w_in[i * e_local:(i + 1) * e_local],
                      full.w_out[i * e_local:(i + 1) * e_local])
            for i in range(n)]


def test_ep_matches_single_shard():
    full = _params()
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(64, D).astype(np.float32))
    ref, aux_ref = moe_layer_p(x, full, "none", 1, capacity_factor=8.0)

    mesh = _mesh()
    shards = _shard_params(full)
    w_in = jnp.stack([s.w_in for s in shards])    # [n, E/n, D, F]
    w_out = jnp.stack([s.w_out for s in shards])

    def body(x, router, w_in, w_out):
        p = MoEParams(router, w_in[0], w_out[0])
        y, aux = moe_layer_p(x, p, "expert", N_SHARD, capacity_factor=8.0)
        return y, aux

    fn = jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(), P("expert"), P("expert")),
        out_specs=(P(), P()), check_vma=False))
    y, aux = fn(x, full.router, w_in, w_out)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=2e-5,
                               atol=2e-6)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-5)


def test_gradients_flow_through_dispatch():
    full = _params(seed=1)
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(32, D).astype(np.float32))

    def loss(params, x):
        y, aux = moe_layer_p(x, params, "none", 1, capacity_factor=8.0)
        return jnp.sum(y ** 2) + 0.01 * aux

    g = jax.grad(loss)(full, x)
    for leaf in jax.tree_util.tree_leaves(g):
        assert np.isfinite(np.asarray(leaf)).all()
    # router receives gradient through the gate
    assert float(jnp.abs(g.router).sum()) > 0


def test_capacity_overflow_drops_tokens():
    """With capacity 1 and many tokens on one expert, overflow outputs are
    exactly zero (residual carries them)."""
    full = _params(seed=2)
    # tokens engineered to route identically: identical inputs
    x = jnp.tile(jnp.asarray(np.random.RandomState(3).randn(1, D),
                             jnp.float32), (16, 1))
    y, _ = moe_layer_p(x, full, "none", 1, capacity_factor=1.0 / 16 * E)
    # capacity = ceil(16 * (E/16) / E) = 1 → only the first token survives
    nz = np.flatnonzero(np.abs(np.asarray(y)).sum(axis=1) > 1e-9)
    assert len(nz) == 1 and nz[0] == 0, nz


# ---------------------------------------------------------------------------
# MoE-EP through the engine alltoall (ISSUE 17): the capacity-routed
# train step in models/transformer.py riding engine.grouped_alltoall
# ---------------------------------------------------------------------------

def _moe_ep_fixture():
    import optax
    import horovod_tpu as hvd
    from horovod_tpu.models.transformer import (
        TransformerConfig, init_params, make_moe_ep_train_step,
        moe_ep_partition)
    hvd.init()
    eng = hvd._engine()
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_seq=16,
                            dtype=jnp.float32, attention="flash",
                            use_moe=True, n_experts=4,
                            moe_capacity_factor=2.0)
    opt = optax.sgd(0.1)
    params = init_params(jax.random.PRNGKey(0), cfg)
    shared, expert = moe_ep_partition(
        params, eng.backend.rank(), eng.backend.size(), cfg)
    step = make_moe_ep_train_step(eng, cfg, opt)
    st = (shared, expert, opt.init({"shared": shared, "expert": expert}))
    rng = np.random.RandomState(0)
    tok = jnp.asarray(rng.randint(0, 64, (2, 16)), jnp.int32)
    tgt = jnp.asarray(rng.randint(0, 64, (2, 16)), jnp.int32)
    return eng, step, st, tok, tgt


def test_moe_ep_engine_step_learns():
    """The engine-alltoall MoE step trains: loss decreases over a few
    steps and both the shared and the expert leaves actually move."""
    eng, step, st, tok, tgt = _moe_ep_fixture()
    eng.replay.invalidate_all("test isolation")
    w1_before = np.asarray(st[1]["w1"]).copy()
    embed_before = np.asarray(st[0]["embed"]).copy()
    losses = []
    for _ in range(5):
        *st, loss = step(*st, tok, tgt)
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], losses
    assert not np.array_equal(np.asarray(st[1]["w1"]), w1_before), \
        "expert weights never updated"
    assert not np.array_equal(np.asarray(st[0]["embed"]), embed_before), \
        "shared weights never updated"


def test_moe_ep_routing_metrics_populate():
    """Per-expert dispatch accounting rides the PR 5 skew machinery:
    hvd_tpu_moe_expert_tokens_total counts by expert index and the
    per-layer hvd_tpu_moe_dispatch_skew gauge lands at >= 1 (max/mean)."""
    from horovod_tpu.metrics import registry
    eng, step, st, tok, tgt = _moe_ep_fixture()
    eng.replay.invalidate_all("test isolation")
    snap0 = registry().snapshot()
    *st, _ = step(*st, tok, tgt)
    snap = registry().snapshot()

    def rows(s, name):
        ent = s.get("counters", {}).get(name) or \
            s.get("gauges", {}).get(name)
        return dict((tuple(sorted(l.items())), v)
                    for l, v in (ent or {}).get("values", []))

    tok_rows = rows(snap, "hvd_tpu_moe_expert_tokens_total")
    base_rows = rows(snap0, "hvd_tpu_moe_expert_tokens_total")
    delta = sum(tok_rows.values()) - sum(base_rows.values())
    # every routed token is counted once per layer (pre-capacity)
    assert delta == 2 * 16 * 2, delta       # B*T tokens x L layers
    skew = rows(snap, "hvd_tpu_moe_dispatch_skew")
    assert any(dict(k).get("layer") == "0" for k in skew), skew
    assert all(v >= 1.0 for v in skew.values())


def test_moe_ep_step_is_bitwise_deterministic():
    """Same params, same batch, fresh replay state: the whole loss
    trajectory repeats bitwise (the engine transport introduces no
    nondeterminism — the PP x MoE acceptance bar, size-1 face)."""
    def trajectory():
        eng, step, st, tok, tgt = _moe_ep_fixture()
        eng.replay.invalidate_all("test isolation")
        out = []
        for _ in range(4):
            *st, loss = step(*st, tok, tgt)
            out.append(float(loss))
        return out
    assert trajectory() == trajectory()


@pytest.mark.perf
def test_perf_smoke_moe_ep_bench():
    """ISSUE 17: the MoE-EP bench emits tokens/s/chip vs the matched
    dense baseline plus the two-slice DCN accounting artifact — no
    timing thresholds, just that the acceptance fields materialize."""
    import horovod_tpu as hvd
    from bench import bench_moe_ep
    hvd.init()
    r = bench_moe_ep(hvd._engine(), steps=2)
    assert r["moe_ep_tokens_per_sec_per_chip"] > 0
    assert r["moe_ep_dense_tokens_per_sec_per_chip"] > 0
    assert r["moe_ep_vs_dense"] > 0
    # two-slice fixture: hierarchical halves the DCN leg (C/(C-1) = 2x
    # at two slices) and the bf16 DCN-leg codec halves it again
    assert r["moe_dispatch_dcn_drop_factor"] == 2.0
    assert r["moe_dispatch_dcn_bytes_hier_8x4"] * 2 == \
        r["moe_dispatch_dcn_bytes_flat_8x4"]
    assert r["moe_dispatch_dcn_bytes_hier_bf16_8x4"] * 2 == \
        r["moe_dispatch_dcn_bytes_hier_8x4"]
