"""Expert-parallel MoE tests: the EP-sharded layer (all-to-all dispatch over
4 expert shards) must match the single-shard reference bit-for-bit given the
same expert weights, gradients must flow, and capacity overflow must drop
tokens to zero (Switch semantics)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.parallel.moe import MoEParams, init_moe, moe_layer_p

E, D, F = 8, 16, 32
N_SHARD = 4


def _mesh(n=N_SHARD):
    return jax.sharding.Mesh(np.array(jax.devices()[:n]), ("expert",))


def _params(seed=0):
    """Full (unsharded) params with E experts."""
    return init_moe(jax.random.PRNGKey(seed), D, F, E, n_expert_shards=1)


def _shard_params(full: MoEParams, n=N_SHARD):
    e_local = E // n
    return [MoEParams(full.router,
                      full.w_in[i * e_local:(i + 1) * e_local],
                      full.w_out[i * e_local:(i + 1) * e_local])
            for i in range(n)]


def test_ep_matches_single_shard():
    full = _params()
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(64, D).astype(np.float32))
    ref, aux_ref = moe_layer_p(x, full, "none", 1, capacity_factor=8.0)

    mesh = _mesh()
    shards = _shard_params(full)
    w_in = jnp.stack([s.w_in for s in shards])    # [n, E/n, D, F]
    w_out = jnp.stack([s.w_out for s in shards])

    def body(x, router, w_in, w_out):
        p = MoEParams(router, w_in[0], w_out[0])
        y, aux = moe_layer_p(x, p, "expert", N_SHARD, capacity_factor=8.0)
        return y, aux

    fn = jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(), P("expert"), P("expert")),
        out_specs=(P(), P()), check_vma=False))
    y, aux = fn(x, full.router, w_in, w_out)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=2e-5,
                               atol=2e-6)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-5)


def test_gradients_flow_through_dispatch():
    full = _params(seed=1)
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(32, D).astype(np.float32))

    def loss(params, x):
        y, aux = moe_layer_p(x, params, "none", 1, capacity_factor=8.0)
        return jnp.sum(y ** 2) + 0.01 * aux

    g = jax.grad(loss)(full, x)
    for leaf in jax.tree_util.tree_leaves(g):
        assert np.isfinite(np.asarray(leaf)).all()
    # router receives gradient through the gate
    assert float(jnp.abs(g.router).sum()) > 0


def test_capacity_overflow_drops_tokens():
    """With capacity 1 and many tokens on one expert, overflow outputs are
    exactly zero (residual carries them)."""
    full = _params(seed=2)
    # tokens engineered to route identically: identical inputs
    x = jnp.tile(jnp.asarray(np.random.RandomState(3).randn(1, D),
                             jnp.float32), (16, 1))
    y, _ = moe_layer_p(x, full, "none", 1, capacity_factor=1.0 / 16 * E)
    # capacity = ceil(16 * (E/16) / E) = 1 → only the first token survives
    nz = np.flatnonzero(np.abs(np.asarray(y)).sum(axis=1) > 1e-9)
    assert len(nz) == 1 and nz[0] == 0, nz
