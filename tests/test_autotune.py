"""Autotuner tests: GP regression, EI acquisition, ParameterManager loop.

Mirrors the role of the reference's autotuning stack
(common/parameter_manager.{h,cc}, common/optim/) — validated here against
synthetic objectives rather than live comm throughput.
"""

import os

import numpy as np
import pytest

from horovod_tpu.autotune import (BayesianOptimizer, GaussianProcessRegressor,
                                  ParameterManager, expected_improvement)

MB = 1024 * 1024


class TestGaussianProcess:
    def test_interpolates_training_points(self):
        x = np.linspace(0, 1, 6)[:, None]
        y = np.sin(2 * np.pi * x[:, 0])
        gp = GaussianProcessRegressor(alpha=1e-10).fit(x, y)
        mean, std = gp.predict(x)
        np.testing.assert_allclose(mean, y, atol=1e-3)
        assert np.all(std < 0.1)

    def test_uncertainty_grows_away_from_data(self):
        x = np.array([[0.0], [0.1]])
        y = np.array([0.0, 0.1])
        gp = GaussianProcessRegressor(length_scale=0.1, alpha=1e-8).fit(
            x, y, optimize_hyperparams=False)
        _, std_near = gp.predict(np.array([[0.05]]))
        _, std_far = gp.predict(np.array([[2.0]]))
        assert std_far[0] > std_near[0]


class TestExpectedImprovement:
    def test_prefers_high_mean(self):
        mean = np.array([0.0, 1.0])
        std = np.array([0.1, 0.1])
        ei = expected_improvement(mean, std, best_y=0.5)
        assert ei[1] > ei[0]

    def test_prefers_high_uncertainty_at_equal_mean(self):
        mean = np.array([0.5, 0.5])
        std = np.array([0.01, 0.5])
        ei = expected_improvement(mean, std, best_y=0.5)
        assert ei[1] > ei[0]


class TestBayesianOptimizer:
    def test_finds_peak_of_smooth_objective(self):
        # maximize -(x-0.7)² over [0,1]
        opt = BayesianOptimizer([(0.0, 1.0)], seed=1)
        for _ in range(25):
            x = opt.suggest()
            y = -float((x[0] - 0.7) ** 2)
            opt.register(x, y)
        best_x, best_y = opt.best()
        assert abs(best_x[0] - 0.7) < 0.12, best_x


class TestParameterManager:
    def _drive(self, pm, score_fn, n_samples=40):
        """Feed synthetic throughput: score depends on current knobs."""
        pm.step_mark(8 * MB)
        for _ in range(n_samples):
            if not pm.active:
                break
            for _ in range(pm._steps_per_sample):
                # synthesize elapsed time so that throughput follows score_fn
                pm._step_start -= 1.0 / score_fn(pm.fusion_threshold_bytes)
                pm.step_mark(8 * MB)

    def test_converges_to_better_threshold(self, tmp_path):
        log = str(tmp_path / "autotune.csv")
        pm = ParameterManager(warmup_samples=1, steps_per_sample=3,
                              max_samples=12, gp_noise=1e-3,
                              initial_threshold=2 * MB, log_path=log)

        # throughput peaks at 64MB threshold (log2 = 26)
        def score(threshold):
            return 1000.0 / (1.0 + (np.log2(threshold) - 26.0) ** 2)

        self._drive(pm, score)
        assert not pm.active  # converged & frozen
        # should end well above the (bad) 2MB start and near the peak
        assert 16 * MB <= pm.fusion_threshold_bytes <= 256 * MB
        with open(log) as f:
            lines = f.read().strip().splitlines()
        assert lines[0].startswith("sample,")
        assert lines[-1].startswith("best,")

    def test_engine_integration(self):
        """HOROVOD_AUTOTUNE=1 retunes engine config live."""
        import horovod_tpu as hvd
        from horovod_tpu.core.state import global_state
        os.environ["HOROVOD_AUTOTUNE"] = "1"
        os.environ["HOROVOD_AUTOTUNE_WARMUP_SAMPLES"] = "0"
        os.environ["HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE"] = "1"
        os.environ["HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES"] = "4"
        try:
            hvd.shutdown()
            hvd.init()
            st = global_state()
            assert st.parameter_manager is not None
            grads = [np.ones((64, 64), np.float32) for _ in range(4)]
            for i in range(8):
                hs = hvd.grouped_allreduce_async(grads, name=f"at{i}")
                for h in hs:
                    hvd.synchronize(h)
            assert st.parameter_manager.n_samples_taken >= 1 or \
                not st.parameter_manager.active
        finally:
            for k in ("HOROVOD_AUTOTUNE", "HOROVOD_AUTOTUNE_WARMUP_SAMPLES",
                      "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE",
                      "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES"):
                os.environ.pop(k, None)
            hvd.shutdown()
            hvd.init()


class TestCategoricalKnobs:
    def test_categorical_dims_in_search_space(self):
        from horovod_tpu.autotune.parameter_manager import ParameterManager
        pm = ParameterManager(
            warmup_samples=0, steps_per_sample=1, max_samples=5,
            categorical=["hierarchical_allreduce", "pallas_pack"],
            categorical_initial={"hierarchical_allreduce": False})
        assert pm.tunes("hierarchical_allreduce")
        assert pm.tunes("pallas_pack")
        assert not pm.tunes("nonexistent")
        assert pm.categorical_value("hierarchical_allreduce") is False
        assert len(pm._bounds) == 4

    def test_tuner_flips_hierarchical_when_it_scores_better(self):
        """Simulated local_size=2 topology where the hierarchical ladder
        makes steps faster: the converged parameters must have the knob ON
        (VERDICT r2 item 5). Scores are synthesized step throughputs —
        hierarchical=True worlds run 2x faster."""
        import time as _time
        from horovod_tpu.autotune.parameter_manager import ParameterManager

        pm = ParameterManager(
            warmup_samples=0, steps_per_sample=1, max_samples=14,
            gp_noise=1e-3,
            categorical=["hierarchical_allreduce"],
            categorical_initial={"hierarchical_allreduce": False})
        nbytes = 4 * 1024 * 1024
        base_step = 0.02
        clock = [0.0]
        real = _time.perf_counter

        def fake_clock():
            return clock[0]

        _time_pm = __import__(
            "horovod_tpu.autotune.parameter_manager",
            fromlist=["time"])
        orig = _time_pm.time.perf_counter
        _time_pm.time.perf_counter = fake_clock
        try:
            while pm.active:
                # synthetic step: hierarchical halves the step time
                hier = pm.categorical_value("hierarchical_allreduce")
                clock[0] += base_step / (2.0 if hier else 1.0)
                pm.step_mark(nbytes)
        finally:
            _time_pm.time.perf_counter = orig
        assert not pm.active
        assert pm.categorical_value("hierarchical_allreduce") is True, \
            "tuner failed to adopt the faster hierarchical configuration"

    def test_engine_applies_categorical_values(self):
        """pm categorical values propagate into the live engine config."""
        import horovod_tpu as hvd
        from horovod_tpu.core.state import global_state
        hvd.init()
        st = global_state()
        eng = st.engine

        class FakePM:
            active = False
            fusion_threshold_bytes = 32 * 1024 * 1024
            cycle_time_ms = 7.0

            def tunes(self, name):
                return name in ("hierarchical_allreduce",
                                "hierarchical_allgather")

            def categorical_value(self, name):
                return True

        old_pm = eng.parameter_manager
        saved = (eng.config.hierarchical_allreduce,
                 eng.config.hierarchical_allgather,
                 eng.config.fusion_threshold_bytes,
                 eng.config.cycle_time_ms)
        try:
            eng.parameter_manager = FakePM()
            hs = hvd.grouped_allreduce_async(
                [np.ones(8, np.float32)], name="catk")
            for h in hs:
                hvd.synchronize(h)
            assert eng.config.hierarchical_allreduce is True
            assert eng.config.hierarchical_allgather is True
            assert eng.config.fusion_threshold_bytes == 32 * 1024 * 1024
        finally:
            eng.parameter_manager = old_pm
            (eng.config.hierarchical_allreduce,
             eng.config.hierarchical_allgather,
             eng.config.fusion_threshold_bytes,
             eng.config.cycle_time_ms) = saved
