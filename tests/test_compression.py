"""Link-aware gradient compression (ISSUE 13).

Unit surface: codec primitives (round-trip error bounds per dtype,
error-feedback residual semantics), the per-link split (ICI legs stay
full precision and bit-exact, only the DCN payload is encoded), the
reducer numerics on the 8-device CPU mesh (hierarchical ladder + the
whole-payload flat/tree fallback + the ZeRO-1 compressed reduce-scatter's
ownership invariant), the compressor-surface parity fixes, the engine's
residual registry invalidation contract, replay re-arm on a codec knob
move, and the SPMD error-feedback path. Real-world trajectory / DCN-drop
acceptance lives in tests/test_multiprocess.py; chaos recovery in
tests/test_chaos.py.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.common.reduce_ops import ReduceOp
from horovod_tpu.ops import collectives as C
from horovod_tpu.ops import compression as comp


def _world_mesh():
    devs = jax.devices()
    return Mesh(np.array(devs), ("world",)), len(devs)


def _rep(mesh, x):
    return jax.device_put(x, NamedSharding(mesh, P()))


def _stacked(mesh, x):
    return jax.device_put(x, NamedSharding(mesh, P("world")))


# ---------------------------------------------------------------------------
# codec primitives
# ---------------------------------------------------------------------------

class TestCodecPrimitives:
    def test_int8_round_trip_error_bound(self):
        x = jnp.asarray(np.random.RandomState(0).randn(512), jnp.float32)
        payload, scale = comp.encode(x, "int8")
        assert payload.dtype == jnp.int8 and scale.shape == (1,)
        back = comp.decode(payload, scale, "int8", jnp.float32)
        amax = float(jnp.max(jnp.abs(x)))
        # symmetric linear quantization: half a step per element
        assert float(jnp.max(jnp.abs(back - x))) <= amax / 127 / 2 + 1e-6

    def test_fp8_round_trip_error_bound(self):
        if comp._FP8_DTYPE is None:
            pytest.skip("no float8 dtype on this jax")
        x = jnp.asarray(np.random.RandomState(1).randn(512), jnp.float32)
        payload, scale = comp.encode(x, "fp8")
        back = comp.decode(payload, scale, "fp8", jnp.float32)
        # e4m3 keeps ~3 mantissa bits: relative error <= 2^-4 of the
        # element (plus the scale's own rounding)
        assert float(jnp.max(jnp.abs(back - x))) <= \
            float(jnp.max(jnp.abs(x))) * 0.07 + 1e-6

    def test_bf16_round_trip(self):
        x = jnp.asarray(np.random.RandomState(2).randn(512), jnp.float32)
        payload, scale = comp.encode(x, "bf16")
        assert payload.dtype == jnp.bfloat16 and scale is None
        back = comp.decode(payload, None, "bf16", jnp.float32)
        assert float(jnp.max(jnp.abs(back - x))) <= \
            float(jnp.max(jnp.abs(x))) * 2 ** -8

    def test_ef_encode_residual_semantics(self):
        """quantize(g + r): the new residual is exactly the quantization
        error of the residual-corrected payload."""
        x = jnp.asarray(np.random.RandomState(3).randn(256), jnp.float32)
        r = jnp.asarray(np.random.RandomState(4).randn(256) * 0.01,
                        jnp.float32)
        payload, scale, new_r = comp.ef_encode(x, r, "int8")
        back = comp.decode(payload, scale, "int8", jnp.float32)
        np.testing.assert_allclose(np.asarray(new_r),
                                   np.asarray(x + r - back), atol=1e-6)
        # residual=None means a fresh (zero) buffer
        p2, s2, r2 = comp.ef_encode(x, None, "int8")
        back2 = comp.decode(p2, s2, "int8", jnp.float32)
        np.testing.assert_allclose(np.asarray(r2),
                                   np.asarray(x - back2), atol=1e-6)

    def test_resolve_codec_rules(self):
        assert comp.resolve_codec("int8", jnp.float32) == "int8"
        assert comp.resolve_codec("none", jnp.float32) == "none"
        # non-float payloads are never quantized
        assert comp.resolve_codec("int8", jnp.int32) == "none"
        assert comp.resolve_codec("bf16", jnp.int64) == "none"
        # bf16 on an already-16-bit float payload is a no-op
        assert comp.resolve_codec("bf16", jnp.bfloat16) == "none"
        assert comp.resolve_codec("bf16", jnp.float32) == "bf16"

    def test_fp8_demotes_to_int8_without_float8(self, monkeypatch):
        monkeypatch.setattr(comp, "_FP8_DTYPE", None)
        monkeypatch.setattr(comp, "_warned_codec", set())
        assert comp.resolve_codec("fp8", jnp.float32) == "int8"

    def test_wire_itemsize(self):
        assert comp.wire_itemsize("none", 4) == 4
        assert comp.wire_itemsize("bf16", 4) == 2
        assert comp.wire_itemsize("fp8", 4) == 1
        assert comp.wire_itemsize("int8", 4) == 1
        assert comp.wire_itemsize("bf16", 2) == 2  # never grows


# ---------------------------------------------------------------------------
# compressor surface (Horovod parity + ISSUE 13 satellite fix)
# ---------------------------------------------------------------------------

class TestCompressorSurface:
    def test_wire_codec_compressors_exported(self):
        assert hvd.Compression.fp8.wire_codec == "fp8"
        assert hvd.Compression.int8.wire_codec == "int8"
        assert hvd.Compression.none.wire_codec is None
        assert hvd.Compression.fp16.wire_codec is None
        # frontend compress/decompress are identity for the wire codecs
        x = jnp.ones((4,), jnp.float32)
        c, ctx = hvd.Compression.int8.compress(x)
        assert c is x and ctx is None
        assert hvd.Compression.int8.decompress(c, ctx) is x

    @pytest.mark.parametrize("cls", [hvd.Compression.fp16,
                                     hvd.Compression.bf16])
    def test_cast_compressor_nonfloat_ctx_is_none(self, cls):
        """Satellite fix: compress leaves int tensors untouched and must
        return ctx=None so decompress is a true no-op (the old ctx=dtype
        issued a pointless astype on every integer bucket)."""
        x = jnp.arange(8, dtype=jnp.int32)
        c, ctx = cls.compress(x)
        assert ctx is None
        assert c.dtype == jnp.int32
        out = cls.decompress(c, ctx)
        assert out is c

    @pytest.mark.parametrize("cls,wire_dtype", [
        (hvd.Compression.fp16, jnp.float16),
        (hvd.Compression.bf16, jnp.bfloat16)])
    def test_cast_compressor_float_round_trip(self, cls, wire_dtype):
        x = jnp.asarray([1.5, -2.25], jnp.float32)
        c, ctx = cls.compress(x)
        assert c.dtype == wire_dtype and ctx == jnp.float32
        assert cls.decompress(c, ctx).dtype == jnp.float32


# ---------------------------------------------------------------------------
# per-link split math
# ---------------------------------------------------------------------------

class TestLinkSplitCodec:
    def test_hierarchical_dcn_leg_encoded_ici_unchanged(self):
        # 4096 fp32 bytes, local_size 4: dcn_raw = 1024, ici = 3072
        base = C.link_split("hierarchical", 4096, 4)
        assert base == {"dcn": 1024, "ici": 3072}
        i8 = C.link_split("hierarchical", 4096, 4, codec="int8",
                          itemsize=4)
        assert i8 == {"dcn": 256, "ici": 3072}   # 4x drop, ICI untouched
        bf = C.link_split("hierarchical", 4096, 4, codec="bf16",
                          itemsize=4)
        assert bf == {"dcn": 512, "ici": 3072}

    def test_flat_fallback_half_encoded(self):
        """The flat/tree fallback is compressed-RS + full-precision AG:
        half the payload movement is encoded, the return half is not —
        the accounting matches the program's actual shape."""
        assert C.link_split("flat", 4096, 1, codec="int8",
                            itemsize=4) == {"flat": 2048 // 4 + 2048}
        assert C.link_split("flat", 4096, 1, codec="bf16",
                            itemsize=4) == {"flat": 2048 // 2 + 2048}
        assert C.link_split("flat", 4096, 1) == {"flat": 4096}
        # a reduce-scatter is all encoded (no return leg)
        assert C.link_split("flat", 4096, 1, kind="reducescatter",
                            codec="int8", itemsize=4) == {"flat": 1024}

    def test_allgather_never_encoded(self):
        assert C.link_split("hierarchical", 4096, 4, kind="allgather",
                            codec="int8", itemsize=4) == {"dcn": 4096}
        assert C.link_split("flat", 4096, 1, kind="allgather",
                            codec="int8", itemsize=4) == {"flat": 4096}

    def test_residual_elems_rules(self):
        # hierarchical: the local-RS shard (padded to local_size)
        assert C.codec_residual_elems("reduce", 1000, 8, 4,
                                      "hierarchical", "int8") == 250
        assert C.codec_residual_elems("reduce", 1001, 8, 4,
                                      "hierarchical", "int8") == 251
        # flat/tree fallback: the whole zero-padded payload (the
        # compressed reduce-scatter's pre-scatter encode)
        assert C.codec_residual_elems("reduce", 1000, 8, 4, "flat",
                                      "int8") == 1000
        assert C.codec_residual_elems("reduce", 1001, 8, 4, "flat",
                                      "int8") == 1008
        # sharded rs leg: the zero-padded flat bucket
        assert C.codec_residual_elems("sharded", 1000, 8, 0, None,
                                      "int8") == C.shard_spec(1000, 8)[0]
        # non-EF codecs carry no residual
        assert C.codec_residual_elems("reduce", 1000, 8, 4, "flat",
                                      "bf16") is None


# ---------------------------------------------------------------------------
# reducer numerics on the 8-device mesh
# ---------------------------------------------------------------------------

class TestCodecReducers:
    def _data(self, n, elems, seed=0):
        rng = np.random.RandomState(seed)
        return rng.randn(n, elems).astype(np.float32)

    def test_flat_int8_error_bound_and_residual(self):
        mesh, n = _world_mesh()
        elems = 1000
        data = self._data(n, elems)
        exact = data.sum(0)
        fn = C.build_grouped_allreduce(
            mesh, "world", ReduceOp.SUM, ((elems,),), [jnp.float32],
            [[0]], algos=("flat",), codecs=("int8",))
        out, new_res = fn(_stacked(mesh, jnp.asarray(data)),
                          _rep(mesh, jnp.zeros((elems,), jnp.float32)))
        # one quantization step (amax/127) of error per contribution
        bound = (np.abs(data).max(axis=1) / 127 / 2).sum() + 1e-5
        assert np.abs(np.asarray(out) - exact).max() <= bound
        # the returned residual is rank 0's own quantization error
        # (process 0 owns device 0's shard of the world view)
        p0, s0 = comp.encode(jnp.asarray(data[0]), "int8")
        want = data[0] - np.asarray(
            comp.decode(p0, s0, "int8", jnp.float32))
        np.testing.assert_allclose(np.asarray(new_res), want, atol=1e-5)

    def test_residual_carry_across_steps(self):
        """quantize(g + r) telescopes: the K-step cumulative decoded sum
        differs from the exact cumulative sum by exactly the FINAL
        residuals (sum_t decoded_t = sum_t x_t + r_0 - r_K per rank), so
        the cumulative error stays bounded by one quantization step while
        fresh per-step quantization accumulates K steps of error."""
        mesh, n = _world_mesh()
        elems = 400
        K = 4
        data = self._data(n, elems, seed=7) * 0.01
        fn = C.build_grouped_allreduce(
            mesh, "world", ReduceOp.SUM, ((elems,),), [jnp.float32],
            [[0]], algos=("flat",), codecs=("int8",))
        arg = _stacked(mesh, jnp.asarray(data))
        zeros = _rep(mesh, jnp.zeros((elems,), jnp.float32))
        cum_ef = np.zeros(elems, np.float32)
        cum_fresh = np.zeros(elems, np.float32)
        res = zeros
        for _ in range(K):
            out_ef, res_arr = fn(arg, res)
            # feed the residual back AS the claimed-replicated global
            # array: each device keeps ITS OWN residual shard (the
            # engine's world-view convention) — a host round-trip would
            # collapse every device onto device 0's residual
            res = res_arr
            cum_ef += np.asarray(out_ef)
            out_fresh, _ = fn(arg, zeros)
            cum_fresh += np.asarray(out_fresh)
        exact = data.sum(0) * K
        err_ef = np.abs(cum_ef - exact).max()
        err_fresh = np.abs(cum_fresh - exact).max()
        # EF cumulative error is bounded by the final residuals — one
        # half-step per contributor — independent of K
        one_step = (np.abs(data).max(axis=1) / 127).sum() + 1e-5
        assert err_ef <= one_step
        assert err_ef < err_fresh

    def test_hierarchical_ici_legs_bit_exact(self):
        """Only the DCN payload is encoded: integer-valued data whose
        quantization grid is exact (amax=127 -> scale=1) must come back
        BITWISE equal to the uncompressed flat sum — any ICI-leg encoding
        would still be exact here, but a whole-payload error would not
        telescope away; combined with the non-exact case below this pins
        the encode to the cross-slice exchange."""
        mesh, n = _world_mesh()
        elems = 512
        rng = np.random.RandomState(11)
        # integer data whose SLICE-LOCAL sums stay in [-127, 127] with
        # amax pinned to exactly 127 in EVERY per-rank shard chunk (the
        # encode sees the post-local-RS shard — elems/local contiguous
        # positions of the slice sum): scale = 1.0 on every chunk, every
        # value on the grid -> the DCN encode is exact end to end
        data = rng.randint(-7, 8, size=(n, elems)).astype(np.float32)
        chunk = elems // 4   # local_size=4 shard length
        for j in range(4):
            data[:, j * chunk] = 0.0
            data[::4, j * chunk] = 127.0   # local idx 0 of each slice
        exact = data.sum(0)
        fn = C.build_grouped_allreduce(
            mesh, "world", ReduceOp.SUM, ((elems,),), [jnp.float32],
            [[0]], local_size=4, algos=("hierarchical",),
            codecs=("int8",))
        res = _rep(mesh, jnp.zeros(
            (C.codec_residual_elems("reduce", elems, n, 4,
                                    "hierarchical", "int8"),),
            jnp.float32))
        out, new_res = fn(_stacked(mesh, jnp.asarray(data)), res)
        np.testing.assert_array_equal(np.asarray(out), exact)
        # exact grid -> zero residual
        assert float(np.abs(np.asarray(new_res)).max()) == 0.0

    def test_hierarchical_int8_error_scales_with_dcn_traffic(self):
        """The hierarchical ladder quantizes the post-local-RS shard (the
        cross-slice contribution), so the error bound is the CROSS count
        (n/local) of quantization steps — not the world count."""
        mesh, n = _world_mesh()
        local = 4
        elems = 1024
        data = self._data(n, elems, seed=5)
        exact = data.sum(0)
        fn = C.build_grouped_allreduce(
            mesh, "world", ReduceOp.SUM, ((elems,),), [jnp.float32],
            [[0]], local_size=local, algos=("hierarchical",),
            codecs=("int8",))
        res = _rep(mesh, jnp.zeros((elems // local,), jnp.float32))
        out, _ = fn(_stacked(mesh, jnp.asarray(data)), res)
        # each slice's local sum has amax <= local * max|x|; cross slices
        # contribute (n/local) half-steps of that scale
        amax = np.abs(data).max() * local
        bound = (n // local) * amax / 127 / 2 + 1e-4
        assert np.abs(np.asarray(out) - exact).max() <= bound

    def test_bf16_codec_no_residual_io(self):
        mesh, n = _world_mesh()
        elems = 256
        data = self._data(n, elems, seed=9)
        fn = C.build_grouped_allreduce(
            mesh, "world", ReduceOp.SUM, ((elems,),), [jnp.float32],
            [[0]], algos=("flat",), codecs=("bf16",))
        outs = fn(_stacked(mesh, jnp.asarray(data)))
        assert len(outs) == 1   # no residual output
        exact = data.sum(0)
        assert np.abs(np.asarray(outs[0]) - exact).max() <= \
            np.abs(data).sum(0).max() * 2 ** -7

    def test_average_op(self):
        mesh, n = _world_mesh()
        elems = 128
        data = self._data(n, elems, seed=13)
        fn = C.build_grouped_allreduce(
            mesh, "world", ReduceOp.AVERAGE, ((elems,),), [jnp.float32],
            [[0]], algos=("flat",), codecs=("int8",))
        out, _ = fn(_stacked(mesh, jnp.asarray(data)),
                    _rep(mesh, jnp.zeros((elems,), jnp.float32)))
        exact = data.mean(0)
        bound = (np.abs(data).max(axis=1) / 127 / 2).sum() / n + 1e-5
        assert np.abs(np.asarray(out) - exact).max() <= bound

    def test_sharded_rs_codec_ownership_and_bound(self):
        """The compressed reduce-scatter keeps the pinned-flat ownership:
        rank r's shard is chunk r of the decoded sum, exactly
        shard_spec's rule."""
        mesh, n = _world_mesh()
        elems = 1000   # non-divisible: exercises the padding
        data = self._data(n, elems, seed=17)
        exact = data.sum(0)
        padded, shard = C.shard_spec(elems, n)

        def upd(shards, state):
            return shards, state

        fn = C.build_sharded_step(
            mesh, "world", ReduceOp.SUM, ((elems,),), [jnp.float32],
            [[0]], (), (), upd, codecs=("int8",))
        out, new_res = fn(_stacked(mesh, jnp.asarray(data)),
                          _rep(mesh, jnp.zeros((padded,), jnp.float32)))
        bound = (np.abs(data).max(axis=1) / 127 / 2).sum() + 1e-5
        assert np.abs(np.asarray(out) - exact).max() <= bound
        # the uncompressed form must agree within the same bound (same
        # ownership: unpack reassembles chunks in rank order)
        fn0 = C.build_sharded_step(
            mesh, "world", ReduceOp.SUM, ((elems,),), [jnp.float32],
            [[0]], (), (), upd)
        (out0,) = fn0(_stacked(mesh, jnp.asarray(data)))
        assert np.abs(np.asarray(out) - np.asarray(out0)).max() <= bound

    def test_replay_step_codec_residual_io(self):
        """The replay builder threads residuals: one extra input/output
        per EF bucket, in replay_residual_layout order."""
        mesh, n = _world_mesh()
        elems = 300
        segs = (("reduce", int(ReduceOp.SUM), 1.0, 1.0,
                 (4, ("hierarchical",), ("int8",)), ((elems,),), ((0,),)),)
        layout = C.replay_residual_layout(segs, n)
        assert layout == [(0, 0, C.codec_residual_elems(
            "reduce", elems, n, 4, "hierarchical", "int8"))]
        fn = C.build_replay_step(mesh, "world", segs, pipeline=True)
        x = _rep(mesh, jnp.ones((elems,), jnp.float32))
        res = _rep(mesh, jnp.zeros((layout[0][2],), jnp.float32))
        outs = fn(x, res)
        assert len(outs) == 2
        # identical contributions quantize exactly when amax aligns or
        # at worst within one step per cross slice
        assert np.abs(np.asarray(outs[0]) - n).max() < 0.1

    def test_seg_algo_spec_codec_field(self):
        local, algos, codecs = C._seg_algo_spec((4, ("flat", "tree")), 2)
        assert codecs == ("none", "none")
        local, algos, codecs = C._seg_algo_spec(
            (4, ("flat",), ("int8",)), 1)
        assert codecs == ("int8",)
        local, algos, codecs = C._seg_algo_spec(2, 1)   # legacy int form
        assert local == 2 and codecs == ("none",)

    def test_spmd_ef_allreduce_p(self):
        """The in-shard_map EF primitive hvd.distributed rides."""
        mesh, n = _world_mesh()
        from jax import shard_map

        def body(x, r):
            out, new_r = C.ef_allreduce_p(x[0], r, "world", "int8",
                                          ReduceOp.SUM)
            return out, new_r

        fn = jax.jit(shard_map(body, mesh=mesh,
                               in_specs=(P("world"), P()),
                               out_specs=(P(), P()), check_vma=False))
        data = self._data(n, 200, seed=21)
        out, new_r = fn(_stacked(mesh, jnp.asarray(data)),
                        _rep(mesh, jnp.zeros((200,), jnp.float32)))
        exact = data.sum(0)
        bound = (np.abs(data).max(axis=1) / 127 / 2).sum() + 1e-5
        assert np.abs(np.asarray(out) - exact).max() <= bound


# ---------------------------------------------------------------------------
# SPMD optimizer path (hvd.distributed(compression=...))
# ---------------------------------------------------------------------------

class TestSPMDDistributedEF:
    def test_int8_trains_close_to_none(self, mesh8):
        import optax
        from jax import shard_map
        from horovod_tpu.optimizer import distributed

        n = 8
        params0 = {"w": jnp.ones((16,), jnp.float32)}
        data = jnp.asarray(
            np.random.RandomState(3).randn(n, 16).astype(np.float32))

        def make_step(compression):
            opt = distributed(optax.sgd(0.05), axis_name="world",
                              compression=compression)

            def body(p, st_ref, x):
                def loss(p):
                    return jnp.sum((p["w"] - x[0]) ** 2)
                g = jax.grad(loss)(p)
                up, st = opt.update(g, st_ref, p)
                return optax.apply_updates(p, up), st

            fn = jax.jit(shard_map(
                body, mesh=mesh8,
                in_specs=(P(), P(), P("world")), out_specs=(P(), P()),
                check_vma=False))
            return opt, fn

        opt_n, fn_n = make_step(hvd.Compression.none)
        opt_q, fn_q = make_step(hvd.Compression.int8)
        pn, sn = dict(params0), opt_n.init(params0)
        pq, sq = dict(params0), opt_q.init(params0)
        assert sq.residual is not None and sn.residual is None
        for _ in range(12):
            pn, sn = fn_n(pn, sn, data)
            pq, sq = fn_q(pq, sq, data)
        err = float(np.abs(np.asarray(pn["w"]) - np.asarray(pq["w"]))
                    .max())
        assert err < 5e-2, err
        # the residual evolved (quantization error was carried)
        assert float(np.abs(np.asarray(
            jax.tree_util.tree_leaves(sq.residual)[0])).max()) > 0

    def test_wire_codec_rejects_non_additive_ops(self):
        import optax
        from horovod_tpu.optimizer import distributed
        with pytest.raises(ValueError, match="Average|Sum"):
            distributed(optax.sgd(0.1), op=hvd.Adasum,
                        compression=hvd.Compression.int8, axis_size=8)


# ---------------------------------------------------------------------------
# engine residual registry + knob plumbing (size-1 world: unit level)
# ---------------------------------------------------------------------------

class TestEngineResidualRegistry:
    def test_fetch_store_invalidate(self):
        hvd.init()
        eng = hvd._engine()
        key = ("gar", "t.#", 0, "flat", "int8", 64, "float32")
        # fresh fetch is zeros
        buf = eng._residual_fetch(key, 64, jnp.float32)
        assert float(np.abs(np.asarray(buf)).max()) == 0.0
        eng._residual_store(key, jnp.ones((64,), jnp.float32))
        got = eng._residual_fetch(key, 64, jnp.float32)
        assert float(np.asarray(got).min()) == 1.0
        # shape drift -> fresh zeros (fusion-layout move)
        assert float(np.abs(np.asarray(
            eng._residual_fetch(key, 32, jnp.float32))).max()) == 0.0
        eng.invalidate_residuals("test")
        assert len(eng._ef_residuals) == 0
        got = eng._residual_fetch(key, 64, jnp.float32)
        assert float(np.abs(np.asarray(got)).max()) == 0.0

    def test_world_version_bump_sweeps_residuals(self):
        hvd.init()
        eng = hvd._engine()
        key = ("gar", "wv.#", 0, "flat", "int8", 8, "float32")
        eng._residual_store(key, jnp.ones((8,), jnp.float32))
        assert key in eng._ef_residuals
        eng.world_version += 1
        try:
            eng._prefetch_gc()
            assert key not in eng._ef_residuals
        finally:
            eng.world_version -= 1

    def test_size1_world_resolves_codec_none(self):
        """A single-rank world moves no wire: the codec is always off,
        whatever the knob or the per-call override says."""
        hvd.init()
        eng = hvd._engine()
        if eng.backend.size() > 1:
            pytest.skip("needs the in-process size-1 world")
        assert eng._call_codec("int8") == "none"
        prev = eng.config.compression
        try:
            eng.config.compression = "int8"
            assert eng._call_codec(None) == "none"
        finally:
            eng.config.compression = prev

    def test_algo_sig_includes_compression_knob(self):
        hvd.init()
        eng = hvd._engine()
        prev = eng.config.compression
        try:
            eng.config.compression = "none"
            a = eng._algo_sig()
            eng.config.compression = "int8"
            b = eng._algo_sig()
            assert a != b
        finally:
            eng.config.compression = prev

    def test_replay_rearms_on_codec_knob_move(self):
        """The PR 10 algo_sig pattern applied to the codec knob: a live
        move of HOROVOD_TPU_COMPRESSION (autotune categorical) rebuilds
        armed replay programs."""
        hvd.init()
        eng = hvd._engine()
        prev = (eng.config.step_replay_warmup, eng.config.compression)
        eng.config.step_replay_warmup = 2
        eng.replay.invalidate_all("test isolation")
        tensors = [jnp.ones((8,), jnp.float32) for _ in range(2)]
        try:
            for i in range(3):
                eng.step_begin()
                hvd.grouped_allreduce(list(tensors), name=f"cc.{i}",
                                      op=hvd.Sum)
                eng.step_end()
            assert eng.replay.replayed_steps >= 1
            armed = [e["armed"] for e in eng.replay._seen.values()
                     if e.get("armed")]
            # the sig grew pipeline knobs in ISSUE 16 — compression sits at
            # the slot _algo_sig documents, not the tail
            assert armed and armed[0].algo_sig[5] == "none"
            eng.config.compression = "int8"
            eng.step_begin()
            hvd.grouped_allreduce(list(tensors), name="cc.9", op=hvd.Sum)
            eng.step_end()
            rearmed = [e["armed"] for e in eng.replay._seen.values()
                       if e.get("armed")]
            assert rearmed and rearmed[0].algo_sig[5] == "int8"
        finally:
            (eng.config.step_replay_warmup,
             eng.config.compression) = prev
            eng.replay.invalidate_all("test isolation")


class TestConfigAndAutotune:
    def test_knob_parses(self, monkeypatch):
        from horovod_tpu.common.env import Config, HOROVOD_TPU_COMPRESSION
        monkeypatch.setenv(HOROVOD_TPU_COMPRESSION, "int8")
        assert Config.from_env().compression == "int8"
        monkeypatch.setenv(HOROVOD_TPU_COMPRESSION, "bogus")
        assert Config.from_env().compression == "none"
        monkeypatch.delenv(HOROVOD_TPU_COMPRESSION)
        assert Config.from_env().compression == "none"

    def test_pm_step_maps_compression_categorical(self):
        hvd.init()
        eng = hvd._engine()
        prev = eng.config.compression

        class FakePM:
            active = False
            fusion_threshold_bytes = eng.config.fusion_threshold_bytes
            cycle_time_ms = eng.config.cycle_time_ms

            def tunes(self, knob):
                return knob == "compression"

            def categorical_value(self, knob):
                return self.val

        pm = FakePM()
        eng.parameter_manager = pm
        try:
            eng._codec_base = "int8"
            pm.val = False
            eng._pm_step(0)
            assert eng.config.compression == "none"
            pm.val = True
            eng._pm_step(0)
            assert eng.config.compression == "int8"
        finally:
            eng.parameter_manager = None
            eng.config.compression = prev
