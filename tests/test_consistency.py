"""Debug-mode cross-rank consistency checking (HOROVOD_TPU_DEBUG_CONSISTENCY).

Adversarial 2-process tests mirroring the reference's mismatched-submission
error cases (test/test_torch.py / test_tensorflow.py error grids; coordinator
validation controller.cc:380-623): mismatched shape / dtype / op / name
across ranks must fail fast with a descriptive error on every rank instead
of hanging.
"""

import os

import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("HVD_TPU_SKIP_MULTIPROC") == "1",
    reason="multi-process tier disabled")


def _mp_env():
    return {
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "HOROVOD_STALL_CHECK_DISABLE": "1",
        "HOROVOD_TPU_DEBUG_CONSISTENCY": "1",
    }


def _worker_shape_mismatch():
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import horovod_tpu as hvd
    from horovod_tpu.common.exceptions import TensorShapeMismatchError
    shape = (4,) if hvd.rank() == 0 else (5,)
    try:
        hvd.allreduce(np.ones(shape), name="t", op=hvd.Sum)
    except TensorShapeMismatchError as e:
        return ("raised", "Mismatched shape" in str(e))
    return ("no-error", None)


def _worker_dtype_mismatch():
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import horovod_tpu as hvd
    from horovod_tpu.common.exceptions import TensorDtypeMismatchError
    dtype = np.float32 if hvd.rank() == 0 else np.int32
    try:
        hvd.allreduce(np.ones(3, dtype=dtype), name="t", op=hvd.Sum)
    except TensorDtypeMismatchError as e:
        return ("raised", "Mismatched dtype" in str(e))
    return ("no-error", None)


def _worker_op_mismatch():
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import horovod_tpu as hvd
    from horovod_tpu.common.exceptions import ConsistencyError
    op = hvd.Sum if hvd.rank() == 0 else hvd.Min
    try:
        hvd.allreduce(np.ones(3), name="t", op=op)
    except ConsistencyError as e:
        return ("raised", "reduce op" in str(e))
    return ("no-error", None)


def _worker_name_mismatch():
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import horovod_tpu as hvd
    from horovod_tpu.common.exceptions import ConsistencyError
    name = "a" if hvd.rank() == 0 else "b"
    try:
        hvd.allreduce(np.ones(3), name=name, op=hvd.Sum)
    except ConsistencyError as e:
        return ("raised", "different tensor name" in str(e))
    return ("no-error", None)


def _worker_matching_ok():
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import horovod_tpu as hvd
    out = np.asarray(hvd.allreduce(np.ones(3), name="ok", op=hvd.Sum))
    # uneven allgather dim0 is legitimate and must pass the checker
    g = np.asarray(hvd.allgather(
        np.zeros((hvd.rank() + 1, 2), np.float32), name="ag"))
    outs = hvd.grouped_allreduce(
        [np.ones(2), np.ones((2, 2))], name="grp", op=hvd.Average)
    # fused broadcast (r4): matching submissions pass the checker too
    bp = hvd.broadcast_parameters(
        {"a": np.full((2,), float(hvd.rank())),
         "b": np.full((3, 2), float(hvd.rank()))}, root_rank=1)
    return (float(out[0]), g.shape[0], len(outs),
            float(np.asarray(bp["a"])[0]))


def _worker_equal_sizes_violation():
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import horovod_tpu as hvd
    from horovod_tpu.common.exceptions import TensorShapeMismatchError
    eng = hvd._engine()
    d0 = 2 if hvd.rank() == 0 else 3
    try:
        # equal_sizes=True is a caller contract (dim 0 matches everywhere);
        # debug mode validates dim 0 for it (unlike plain allgather, where
        # uneven dim 0 is legitimate)
        eng.allgather(np.ones((d0, 2), np.float32), name="eq",
                      equal_sizes=True).synchronize()
    except TensorShapeMismatchError as e:
        return ("raised", "Mismatched shape" in str(e))
    return ("no-error", None)


def _worker_grouped_broadcast_mismatch():
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import horovod_tpu as hvd
    from horovod_tpu.common.exceptions import TensorShapeMismatchError
    shape = (2, 2) if hvd.rank() == 0 else (3, 2)
    try:
        hvd.broadcast_parameters({"w": np.ones(shape)}, root_rank=0)
    except TensorShapeMismatchError as e:
        return ("raised", "Mismatched shape" in str(e))
    return ("no-error", None)


@pytest.mark.integration
@pytest.mark.parametrize("worker,desc", [
    (_worker_shape_mismatch, "shape"),
    (_worker_dtype_mismatch, "dtype"),
    (_worker_op_mismatch, "op"),
    (_worker_name_mismatch, "name"),
    (_worker_grouped_broadcast_mismatch, "grouped-broadcast-shape"),
    (_worker_equal_sizes_violation, "equal-sizes-contract"),
])
def test_mismatch_raises_on_every_rank(worker, desc):
    from horovod_tpu.runner import run
    results = run(worker, np=2, env=_mp_env())
    assert results == [("raised", True), ("raised", True)], (desc, results)


@pytest.mark.integration
def test_matching_submissions_pass():
    from horovod_tpu.runner import run
    results = run(_worker_matching_ok, np=2, env=_mp_env())
    assert results == [(2.0, 3, 2, 1.0), (2.0, 3, 2, 1.0)], results
