"""Step-health layer tests (ISSUE 20): detector math (rolling
median/MAD baselines, warmup gate, edge-triggered classification),
flight-dump rate limiting, HBM sampler degradation, the one-branch
disabled mode, a perf-marked overhead smoke, and the np=2 acceptance —
a delay failpoint armed on rank 1 mid-run must surface as a
``straggler_drift`` anomaly naming rank 1, write a flight dump, and
show up in the Prometheus scrape."""

import json
import os
import re
import time
import urllib.request

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu import metrics as hmetrics
from horovod_tpu.observability import (AnomalyDetector, FlightDumper,
                                       HBMSampler, RollingBaseline,
                                       StepDigest, StepHealthMonitor)


@pytest.fixture
def isolated_registry():
    """Swap in a fresh process registry: instruments these tests bump
    (anomaly counters, HBM gauges) must not leak into the KV server's
    merged scrape that the health-report tests read."""
    with hmetrics._registry_lock:
        saved = hmetrics._registry
        hmetrics._registry = hmetrics.Registry()
    try:
        yield
    finally:
        with hmetrics._registry_lock:
            hmetrics._registry = saved


def _digest(step, wall, wait=0.0, dispatches=2, wire=1024.0, fallbacks=0):
    return StepDigest(
        step=step, wall_s=wall, dispatches=dispatches, wire_bytes=wire,
        wire_by_link={"flat": wire}, collective_wait_s=wait,
        wait_by_kind={"allreduce": wait}, replay_replayed=0,
        replay_fallbacks=fallbacks, replay_armed=False, prefetch_hits=0,
        bucket_fill_pct=0.0, compression_saved=0.0)


def _warm(det, n=12, wall=0.010, wait=0.004, **kw):
    """Feed n baseline digests with deterministic jitter so the MAD is
    small but nonzero."""
    for i in range(n):
        det.observe(_digest(i, wall + 1e-4 * (i % 3),
                            wait=wait + 1e-4 * (i % 2), **kw))
    return n


# ---------------------------------------------------------------------------
# RollingBaseline: median/MAD math and the warmup gate
# ---------------------------------------------------------------------------

class TestRollingBaseline:
    def test_median_mad_match_numpy(self):
        rng = np.random.RandomState(7)
        vals = list(rng.uniform(1.0, 5.0, size=40))
        base = RollingBaseline(window=64, warmup=4)
        for v in vals:
            base.update(v)
        assert base.median == pytest.approx(np.median(vals))
        assert base.mad == pytest.approx(
            np.median(np.abs(np.asarray(vals) - np.median(vals))))

    def test_window_bounds_history(self):
        base = RollingBaseline(window=8, warmup=2)
        for v in range(100):
            base.update(float(v))
        # only the last 8 samples (92..99) remain
        assert base.median == pytest.approx(np.median(range(92, 100)))
        assert len(base) == 8

    def test_warmup_gate(self):
        base = RollingBaseline(window=16, warmup=6)
        for i in range(5):
            base.update(1.0)
            assert not base.ready
            # a wild outlier scores 0.0 until the gate opens
            assert base.deviation(100.0) == 0.0
        base.update(1.0)
        assert base.ready
        assert base.deviation(100.0) > 0.0

    def test_floor_prevents_hair_trigger(self):
        # perfectly constant baseline -> MAD 0; the floor keeps the
        # deviation finite and proportional
        base = RollingBaseline(window=16, warmup=4, floor=0.5)
        for _ in range(8):
            base.update(10.0)
        assert base.mad == 0.0
        assert base.deviation(11.0) == pytest.approx(2.0)


# ---------------------------------------------------------------------------
# AnomalyDetector: classification rules, edge triggering
# ---------------------------------------------------------------------------

class TestAnomalyDetector:
    def test_no_anomalies_during_warmup(self):
        det = AnomalyDetector(window=32, warmup=8)
        for i in range(7):
            # wild values, but the gate is closed
            assert det.observe(_digest(i, 0.010 * (i + 1),
                                       wire=1024.0 * (i + 1))) == []

    def test_spike_with_flat_wait_is_straggler_drift(self):
        det = AnomalyDetector(window=32, warmup=8)
        n = _warm(det)
        out = det.observe(_digest(n, wall=0.100, wait=0.004), rank=1)
        classes = {a.cls for a in out}
        assert "step_time_spike" in classes
        assert "straggler_drift" in classes
        drift = next(a for a in out if a.cls == "straggler_drift")
        assert "rank 1 is the straggler" in drift.detail
        assert "local to rank 1" in drift.detail

    def test_spike_with_spiking_wait_is_straggler_wait(self):
        det = AnomalyDetector(window=32, warmup=8)
        n = _warm(det)
        out = det.observe(_digest(n, wall=0.100, wait=0.090), rank=0)
        classes = {a.cls for a in out}
        assert "step_time_spike" in classes
        assert "straggler_wait" in classes
        assert "straggler_drift" not in classes

    def test_spike_is_edge_triggered(self):
        det = AnomalyDetector(window=32, warmup=8)
        n = _warm(det)
        first = det.observe(_digest(n, wall=0.100, wait=0.004))
        assert any(a.cls == "step_time_spike" for a in first)
        # staying in the spike regime emits nothing new
        again = det.observe(_digest(n + 1, wall=0.100, wait=0.004))
        assert not any(a.cls == "step_time_spike" for a in again)

    def test_sustained_regression_fires_once_per_episode(self):
        det = AnomalyDetector(window=64, warmup=8, sustain=3)
        n = _warm(det)
        seen = []
        for i in range(6):
            seen += det.observe(_digest(n + i, wall=0.013, wait=0.004))
        sustained = [a for a in seen if a.cls == "sustained_regression"]
        assert len(sustained) == 1
        assert "consecutive steps above baseline" in sustained[0].detail

    def test_dispatch_change_names_replay_fallback(self):
        det = AnomalyDetector(window=32, warmup=8)
        n = _warm(det)
        out = det.observe(_digest(n, wall=0.010, wait=0.004,
                                  dispatches=9, fallbacks=1))
        change = [a for a in out if a.cls == "dispatch_change"]
        assert len(change) == 1
        assert "replay fell back to eager dispatch" in change[0].detail
        # regime persists -> edge-triggered, no repeat
        for i in range(3):
            more = det.observe(_digest(n + 1 + i, wall=0.010, wait=0.004,
                                       dispatches=9, fallbacks=0))
            assert not any(a.cls == "dispatch_change" for a in more)

    def test_wire_shift(self):
        det = AnomalyDetector(window=32, warmup=8)
        n = _warm(det)
        out = det.observe(_digest(n, wall=0.010, wait=0.004, wire=65536.0))
        assert any(a.cls == "wire_shift" for a in out)


# ---------------------------------------------------------------------------
# FlightDumper: rate limit, swallowed dump failures
# ---------------------------------------------------------------------------

class TestFlightDumper:
    def test_rate_limit(self, isolated_registry):
        calls = []

        def dump():
            calls.append(1)
            return "/tmp/flight.json"

        fd = FlightDumper(dump, min_interval=3600.0)
        assert fd(trigger="step_time_spike") == "/tmp/flight.json"
        # a storm of triggers inside the interval is one dump
        for _ in range(10):
            assert fd(trigger="step_time_spike") is None
        assert len(calls) == 1

    def test_zero_interval_always_dumps(self, isolated_registry):
        calls = []
        fd = FlightDumper(lambda: calls.append(1) or "/x", min_interval=0.0)
        fd()
        fd()
        assert len(calls) == 2

    def test_dump_failure_is_swallowed(self, isolated_registry):
        def bad():
            raise OSError("disk full")

        fd = FlightDumper(bad, min_interval=0.0)
        assert fd(trigger="manual") is None  # no raise


# ---------------------------------------------------------------------------
# HBMSampler: graceful degradation off-device
# ---------------------------------------------------------------------------

class TestHBMSampler:
    def test_unsupported_platform_disables_after_first_sample(self, isolated_registry):
        probes = []

        def stats():
            probes.append(1)
            return None  # CPU-style: no memory_stats

        s = HBMSampler(stats_fn=stats)
        assert s.sample() is None
        assert s.sample() is None
        assert len(probes) == 1  # detected once, never probed again
        assert s.last() == (None, None)

    def test_watermark_tracks_last_sample(self, isolated_registry):
        s = HBMSampler(stats_fn=lambda: {
            "bytes_in_use": 1 << 30, "peak_bytes_in_use": 2 << 30,
            "bytes_limit": 16 << 30})
        out = s.sample()
        assert out["bytes_in_use"] == 1 << 30
        assert s.last() == (1 << 30, 2 << 30)

    def test_raising_stats_fn_degrades(self, isolated_registry):
        def boom():
            raise NotImplementedError("no memory_stats on this runtime")

        s = HBMSampler(stats_fn=boom)
        assert s.sample() is None
        assert s.sample() is None  # disabled, not retried


# ---------------------------------------------------------------------------
# Disabled mode: exactly one branch on the step path
# ---------------------------------------------------------------------------

class TestDisabledMode:
    def test_step_health_0_leaves_engine_health_none(self, monkeypatch):
        from horovod_tpu.core.state import global_state
        monkeypatch.setenv("HOROVOD_TPU_STEP_HEALTH", "0")
        hvd.shutdown()
        hvd.init()
        try:
            gs = global_state()
            assert gs.engine.health is None
            assert gs.step_health is None
            # steps still work, no digests anywhere
            with hvd.step():
                hvd.allreduce(np.ones(2, np.float32), name="shd.off",
                              op=hvd.Sum)
        finally:
            hvd.shutdown()
        monkeypatch.delenv("HOROVOD_TPU_STEP_HEALTH")
        hvd.init()
        try:
            assert global_state().engine.health is not None
        finally:
            hvd.shutdown()

    def test_step_path_has_exactly_one_health_branch(self):
        """The acceptance bar: disabled mode adds exactly one is-None
        check to the step path (the PR 3 engine.trace discipline)."""
        import horovod_tpu.core.engine as engine_mod
        import inspect
        src = inspect.getsource(engine_mod)
        assert len(re.findall(r"self\.health is not None", src)) == 1
        assert not re.findall(r"self\.health\b", inspect.getsource(
            engine_mod.Engine.step_begin))


# ---------------------------------------------------------------------------
# Perf smoke: digest + detector overhead per step
# ---------------------------------------------------------------------------

class _FakeEngine:
    def __init__(self):
        self.dispatch_count = 0
        self.step_index = 0


@pytest.mark.perf
def test_step_health_overhead_under_one_percent(isolated_registry):
    """ISSUE 20 acceptance: on_step_end (digest assembly + baseline
    update + classification) costs < 1% of a 10 ms reference step."""
    eng = _FakeEngine()
    mon = StepHealthMonitor(eng, rank=0)
    costs = []
    for _ in range(300):
        eng.dispatch_count += 3
        eng.step_index += 1
        t0 = time.perf_counter()
        mon.on_step_end()
        costs.append(time.perf_counter() - t0)
    costs.sort()
    median = costs[len(costs) // 2]
    assert median < 100e-6, f"median on_step_end cost {median * 1e6:.1f} us"
    assert len(mon.recent()) == 300


# ---------------------------------------------------------------------------
# health_report --format=json against a live 2-rank scrape
# ---------------------------------------------------------------------------

def _load_tool(name):
    import importlib.util
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(repo, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _rank_snap(rank, anomalies=0.0):
    snap = {
        "enabled": True,
        "counters": {
            "hvd_tpu_steps_total": {"help": "s", "values": [[{}, 50.0]]},
        },
        "gauges": {
            "hvd_tpu_hbm_bytes": {"help": "h", "values": [
                [{"kind": "in_use"}, 4.0e9], [{"kind": "peak"}, 6.0e9],
                [{"kind": "limit"}, 16.0e9]]},
        },
        "histograms": {
            "hvd_tpu_step_seconds": {"help": "st", "values": [
                [{}, {"sum": 0.55, "count": 50,
                      "buckets": [[0.008, 10], [0.016, 45],
                                  ["+Inf", 50]]}]]},
        },
        "events": {},
    }
    if anomalies:
        snap["counters"]["hvd_tpu_step_anomalies_total"] = {
            "help": "a",
            "values": [[{"class": "straggler_drift"}, anomalies]]}
    return snap


class TestHealthReportJSON:
    """ISSUE 20 satellite: ``--format=json`` emits the check.py-shaped
    verdict and exits nonzero when any section is red.

    Every test takes ``isolated_registry``: the in-process KV server
    merges the server process's OWN registry into ``GET /metrics``, so
    without isolation the hundreds of tests that ran earlier in the
    suite leak real step histograms and anomaly counters into the
    scrape and flip the verdict."""

    def _serve(self, snaps):
        from horovod_tpu.metrics import publish_snapshot
        from horovod_tpu.runner.http_server import KVStoreServer
        server = KVStoreServer(("127.0.0.1", 0))
        server.start()
        for rank, snap in enumerate(snaps):
            publish_snapshot(("127.0.0.1", server.port), rank, snap)
        return server

    def test_green_cluster_exits_zero(self, capsys, isolated_registry):
        health = _load_tool("health_report")
        server = self._serve([_rank_snap(0), _rank_snap(1)])
        try:
            rc = health.main(["--url", f"http://127.0.0.1:{server.port}",
                              "--format=json"])
        finally:
            server.stop()
        verdict = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert verdict["ok"] is True
        sh = verdict["checks"]["step_health"]
        assert sh["ok"] is True
        assert sh["stats"]["steps_observed"] == 100
        assert sh["stats"]["step_time_p50_ms"] is not None
        assert sh["stats"]["hbm_min_headroom_bytes"] == pytest.approx(12.0e9)

    def test_anomalies_turn_step_health_red(self, capsys, isolated_registry):
        health = _load_tool("health_report")
        server = self._serve([_rank_snap(0),
                              _rank_snap(1, anomalies=3.0)])
        try:
            rc = health.main(["--url", f"http://127.0.0.1:{server.port}",
                              "--format=json"])
        finally:
            server.stop()
        verdict = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert verdict["ok"] is False
        sh = verdict["checks"]["step_health"]
        assert sh["ok"] is False
        assert any("straggler_drift" in e for e in sh["errors"])

    def test_text_mode_renders_slo_section(self, capsys, isolated_registry):
        health = _load_tool("health_report")
        server = self._serve([_rank_snap(0), _rank_snap(1)])
        try:
            rc = health.main(["--url", f"http://127.0.0.1:{server.port}"])
        finally:
            server.stop()
        out = capsys.readouterr().out
        assert rc == 0
        assert "step health / SLO" in out


# ---------------------------------------------------------------------------
# np=2 acceptance: delay failpoint on rank 1 -> straggler_drift names
# rank 1, flight dump on disk, anomaly counter in the scrape
# ---------------------------------------------------------------------------

def _worker_step_health():
    import os
    import urllib.request
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import horovod_tpu as hvd
    from horovod_tpu import faults
    from horovod_tpu import metrics as hmetrics
    from horovod_tpu.core.state import global_state

    rank = hvd.rank()
    warm = 14

    def one_step(i):
        with hvd.step():
            out = hvd.allreduce(np.ones(64, np.float32),
                                name=f"sh.b{i}", op=hvd.Sum)
        return out

    for i in range(warm):
        one_step(i)
    # mid-run: rank 1 goes slow — an existing delay failpoint at the
    # enqueue seam, rank-local (the sleep runs BEFORE the handle's
    # enqueue timestamp, so rank 1's own collective wait stays flat)
    if rank == 1:
        faults.arm("engine.enqueue=3*delay(0.25)")
    for i in range(warm, warm + 6):
        one_step(i)
    faults.disarm()

    mon = global_state().step_health
    anomalies = mon.recent_anomalies()
    dump_path = os.path.join(os.environ["HOROVOD_TPU_TRACE_DUMP_DIR"],
                             f"hvd_tpu_flight_rank{rank}.json")

    snap = hvd.metrics_snapshot()
    addr = os.environ["HOROVOD_GLOO_RENDEZVOUS_ADDR"]
    port = int(os.environ["HOROVOD_GLOO_RENDEZVOUS_PORT"])
    hmetrics.publish_snapshot((addr, port), rank, snap)
    # poll the KV for every rank's publish — NOT a barrier (a collective
    # here would advance counters past the returned snapshot)
    from horovod_tpu.runner.http_client import read_data_from_kvstore
    for r in range(hvd.size()):
        read_data_from_kvstore(addr, port, "metrics", str(r), timeout=30)
    text = None
    if rank == 0:
        with urllib.request.urlopen(f"http://{addr}:{port}/metrics",
                                    timeout=15) as resp:
            text = resp.read().decode()
    return {
        "rank": rank,
        "classes": sorted({a.cls for a in anomalies}),
        "details": [a.detail for a in anomalies],
        "anomaly_count": mon.anomaly_count,
        "digests": len(mon.recent()),
        "dump_exists": os.path.exists(dump_path),
        "text": text,
    }


@pytest.mark.integration
@pytest.mark.skipif(os.environ.get("HVD_TPU_SKIP_MULTIPROC") == "1",
                    reason="multi-process tier disabled")
def test_two_rank_straggler_anomaly_end_to_end(tmp_path):
    """ISSUE 20 acceptance: a delay failpoint armed on rank 1 mid-run
    produces a straggler-drift anomaly that names rank 1, an automatic
    flight dump on disk, and an anomaly counter visible in the
    Prometheus scrape."""
    from horovod_tpu.runner import run
    env = {
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "HOROVOD_STALL_CHECK_DISABLE": "1",
        "HOROVOD_TPU_METRICS_INTERVAL": "3600",
        # keep every op on the eager path so the enqueue failpoint and
        # the per-op latency histogram stay live under the delay
        "HOROVOD_TPU_STEP_REPLAY": "0",
        "HOROVOD_TPU_TRACE_DUMP_DIR": str(tmp_path),
    }
    results = run(_worker_step_health, np=2, env=env)
    r0 = next(r for r in results if r["rank"] == 0)
    r1 = next(r for r in results if r["rank"] == 1)
    assert r0["digests"] == 20 and r1["digests"] == 20

    # the delayed rank detects ITSELF: step time spiked while its own
    # collective wait stayed flat
    assert "straggler_drift" in r1["classes"], r1
    assert any("rank 1 is the straggler" in d for d in r1["details"]), r1
    # the healthy rank saw its wait spike (waiting on rank 1)
    assert "step_time_spike" in r0["classes"], r0

    # automatic flight dump (rate-limited) on the anomalous rank
    assert r1["dump_exists"], "anomaly produced no flight dump"
    dump = tmp_path / "hvd_tpu_flight_rank1.json"
    with open(dump) as f:
        assert json.load(f)["otherData"]["flight_recorder"] is True

    # anomaly counter rides the normal publish -> scrape path
    assert r0["text"], "rank 0 scraped nothing"
    anom_lines = [ln for ln in r0["text"].splitlines()
                  if ln.startswith("hvd_tpu_step_anomalies_total{")]
    assert anom_lines, "scrape carries no step anomaly counter"
    assert any('class="straggler_drift"' in ln for ln in anom_lines), \
        anom_lines
