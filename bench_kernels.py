"""Kernel micro-benchmarks: Pallas vs lax for the Adasum combine and the
fusion packer (VERDICT r1 #3). Prints one JSON line per comparison.

Timing uses dependent chaining + host fetch (see bench.py: on the tunneled
TPU backend block_until_ready returns early)."""

from __future__ import annotations

import json
import time

import numpy as np


def _time(fn, args, iters=20):
    import jax
    out = fn(*args)
    float(np.asarray(out).ravel()[0])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    float(np.asarray(out).ravel()[0])
    return (time.perf_counter() - t0) / iters


def main():
    import jax
    import jax.numpy as jnp
    from horovod_tpu.ops.adasum import adasum_combine
    from horovod_tpu.ops.pallas_kernels import (adasum_combine_pallas,
                                                pack_pallas)
    from horovod_tpu.ops.collectives import build_pack

    rng = np.random.RandomState(0)
    for n, dtype in [(1 << 20, jnp.float32), (1 << 24, jnp.float32),
                     (1 << 24, jnp.bfloat16)]:
        a = jnp.asarray(rng.randn(n), dtype)
        b = jnp.asarray(rng.randn(n), dtype)
        lax_fn = jax.jit(adasum_combine)
        t_lax = _time(lax_fn, (a, b))
        try:
            t_pl = _time(adasum_combine_pallas, (a, b))
        except Exception as e:
            t_pl = None
            err = f"{type(e).__name__}: {str(e)[:120]}"
        print(json.dumps({
            "bench": "adasum_combine", "n": n, "dtype": str(dtype.__name__),
            "lax_ms": round(t_lax * 1e3, 3),
            "pallas_ms": round(t_pl * 1e3, 3) if t_pl else None,
            "winner": ("pallas" if t_pl and t_pl < t_lax else "lax"),
            **({} if t_pl else {"pallas_error": err}),
        }))

    for count, size in [(100, 1024), (200, 1024), (160, 4096)]:
        ts = [jnp.asarray(rng.randn(size), jnp.float32)
              for _ in range(count)]
        shapes = tuple(tuple(t.shape) for t in ts)
        concat_fn = build_pack(shapes, jnp.float32)
        t_concat = _time(concat_fn, ts)
        try:
            t_pl = _time(lambda *xs: pack_pallas(xs), ts)
        except Exception as e:
            t_pl = None
            err = f"{type(e).__name__}: {str(e)[:120]}"
        print(json.dumps({
            "bench": "fusion_pack", "tensors": count, "each": size,
            "concat_ms": round(t_concat * 1e3, 3),
            "pallas_ms": round(t_pl * 1e3, 3) if t_pl else None,
            "winner": ("pallas" if t_pl and t_pl < t_concat else "concat"),
            **({} if t_pl else {"pallas_error": err}),
        }))


if __name__ == "__main__":
    main()
