"""Kernel micro-benchmarks: Pallas vs lax for the Adasum combine and the
fusion packer (VERDICT r1 #3). Prints one JSON line per comparison.

Timing uses dependent chaining + host fetch (see bench.py: on the tunneled
TPU backend block_until_ready returns early)."""

from __future__ import annotations

import json
import time

import numpy as np


def _time(fn, args, iters=20):
    import jax
    out = fn(*args)
    float(np.asarray(out).ravel()[0])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    float(np.asarray(out).ravel()[0])
    return (time.perf_counter() - t0) / iters


def main():
    import jax
    import jax.numpy as jnp
    from horovod_tpu.ops.adasum import adasum_combine
    from horovod_tpu.ops.pallas_kernels import (adasum_combine_pallas,
                                                pack_pallas)
    from horovod_tpu.ops.collectives import build_pack

    rng = np.random.RandomState(0)
    for n, dtype in [(1 << 20, jnp.float32), (1 << 24, jnp.float32),
                     (1 << 24, jnp.bfloat16)]:
        a = jnp.asarray(rng.randn(n), dtype)
        b = jnp.asarray(rng.randn(n), dtype)
        lax_fn = jax.jit(adasum_combine)
        t_lax = _time(lax_fn, (a, b))
        try:
            t_pl = _time(adasum_combine_pallas, (a, b))
        except Exception as e:
            t_pl = None
            err = f"{type(e).__name__}: {str(e)[:120]}"
        print(json.dumps({
            "bench": "adasum_combine", "n": n, "dtype": str(dtype.__name__),
            "lax_ms": round(t_lax * 1e3, 3),
            "pallas_ms": round(t_pl * 1e3, 3) if t_pl else None,
            "winner": ("pallas" if t_pl and t_pl < t_lax else "lax"),
            **({} if t_pl else {"pallas_error": err}),
        }))

    for count, size in [(100, 1024), (200, 1024), (160, 4096)]:
        ts = [jnp.asarray(rng.randn(size), jnp.float32)
              for _ in range(count)]
        shapes = tuple(tuple(t.shape) for t in ts)
        concat_fn = build_pack(shapes, jnp.float32)
        t_concat = _time(concat_fn, ts)
        try:
            t_pl = _time(lambda *xs: pack_pallas(xs), ts)
        except Exception as e:
            t_pl = None
            err = f"{type(e).__name__}: {str(e)[:120]}"
        print(json.dumps({
            "bench": "fusion_pack", "tensors": count, "each": size,
            "concat_ms": round(t_concat * 1e3, 3),
            "pallas_ms": round(t_pl * 1e3, 3) if t_pl else None,
            "winner": ("pallas" if t_pl and t_pl < t_concat else "concat"),
            **({} if t_pl else {"pallas_error": err}),
        }))

    _bench_attention()
    _bench_ring_segment()


def _bench_attention():
    """Attention kernel comparison (fwd+bwd, marginal scan timing) — the
    measurement behind flash_attention_local's splash-first default. Only
    meaningful on real TPU (off-TPU all paths fall back to the
    materialized reference)."""
    import math
    from functools import partial
    import jax
    import jax.numpy as jnp
    from jax import lax
    from horovod_tpu.parallel.flash_attention import (flash_attention_local,
                                                      splash_available)
    from horovod_tpu.parallel.ring_attention import local_attention

    B, H, T, D = 4, 16, 2048, 128
    fl = 4 * B * H * T * T * D // 2 * 3  # causal fwd + 2x bwd

    def marginal(att):
        # distinct q/k/v: identical operands would let XLA exploit the
        # symmetry of q·qᵀ in the materialized path
        q0, k0, v0 = (jax.random.normal(jax.random.PRNGKey(i), (B, T, H, D),
                                        jnp.bfloat16) for i in range(3))

        def loss(q, k, v):
            return jnp.sum(att(q, k, v).astype(jnp.float32) ** 2)

        @partial(jax.jit, static_argnums=0)
        def run(iters, q, k, v):
            def body(c, _):
                q, k, v, acc = c
                # full backward (dq, dk, dv) so every kernel pays the same
                # work — argnums=0 alone lets XLA dead-code-eliminate the
                # dK/dV matmuls of the materialized path
                l, (gq, gk, gv) = jax.value_and_grad(
                    loss, argnums=(0, 1, 2))(q, k, v)
                eps = jnp.bfloat16(1e-9)
                return (q + gq * eps, k + gk * eps, v + gv * eps,
                        acc + l), 0.
            (q, k, v, acc), _ = lax.scan(
                body, (q, k, v, jnp.zeros((), jnp.float32)), None,
                length=iters)
            return acc
        for it in (4, 24):
            float(np.asarray(run(it, q0, k0, v0)))
        t0 = time.perf_counter()
        float(np.asarray(run(4, q0, k0, v0)))
        d1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        float(np.asarray(run(24, q0, k0, v0)))
        d2 = time.perf_counter() - t0
        return (d2 - d1) / 20

    results = {}
    if jax.default_backend() == "tpu":
        import os
        saved = os.environ.get("HOROVOD_SPLASH")
        try:
            results["materialized"] = marginal(
                lambda q, k, v: local_attention(q, k, v, causal=True))
            os.environ["HOROVOD_SPLASH"] = "0"
            results["flash_tuned"] = marginal(
                lambda q, k, v: flash_attention_local(q, k, v, causal=True))
            os.environ["HOROVOD_SPLASH"] = "1"
            if splash_available():
                results["splash"] = marginal(
                    lambda q, k, v: flash_attention_local(q, k, v,
                                                          causal=True))
        finally:
            if saved is None:
                os.environ.pop("HOROVOD_SPLASH", None)
            else:
                os.environ["HOROVOD_SPLASH"] = saved
    print(json.dumps({
        "bench": "attention_fwd_bwd", "shape": f"B{B} H{H} T{T} D{D} causal",
        **{f"{k}_ms": round(v * 1e3, 2) for k, v in results.items()},
        **{f"{k}_tflops": round(fl / v / 1e12, 1)
           for k, v in results.items()},
        "winner": (min(results, key=results.get) if results
                   else "n/a (not on TPU)"),
    }))


def _bench_ring_segment():
    """Ring per-segment kernel comparison: the Pallas segment path
    (stock flash fwd-with-residuals + global-lse dq/dkv backward) vs the
    chunked pure-JAX inner that CPU and 128-unaligned blocks use — the
    number that justifies routing multi-chip rings through Pallas
    (r4 measured the old chunked inner ~3x slower; the r5 whole-ring
    design makes the Pallas path the default)."""
    from functools import partial
    import jax
    import jax.numpy as jnp
    from jax import lax
    from horovod_tpu.parallel import ring_attention as ra

    if jax.default_backend() != "tpu":
        print(json.dumps({"bench": "ring_segment", "skipped": "not on TPU"}))
        return

    B, H, D = 1, 16, 128

    def marginal(S, seg_fwd, seg_bwd):
        q0, k0, v0 = (jax.random.normal(jax.random.PRNGKey(i), (B, H, S, D),
                                        jnp.bfloat16) for i in range(3))

        @partial(jax.jit, static_argnums=0)
        def run(iters, q, k, v):
            def body(c, _):
                q, k, v, acc = c
                o, lse = seg_fwd(q, k, v, True)
                do = o.astype(jnp.bfloat16)
                di = jnp.sum(o * o, axis=-1)
                dq, dk, dv = seg_bwd(q, k, v, lse, do, di, True)
                eps = jnp.bfloat16(1e-9)
                return (q + dq.astype(q.dtype) * eps,
                        k + dk.astype(q.dtype) * eps,
                        v + dv.astype(q.dtype) * eps,
                        acc + jnp.sum(lse)), 0.
            (q, k, v, acc), _ = lax.scan(
                body, (q, k, v, jnp.zeros((), jnp.float32)), None,
                length=iters)
            return acc
        # sub-2ms kernels need a 100-step span to clear the tunnel's
        # per-fetch noise; median of 3 marginals (bench.py convention)
        i1, i2 = 8, 108
        for it in (i1, i2):
            float(np.asarray(run(it, q0, k0, v0)))
        marg = []
        for _ in range(3):
            t0 = time.perf_counter()
            float(np.asarray(run(i1, q0, k0, v0)))
            d1 = time.perf_counter() - t0
            t0 = time.perf_counter()
            float(np.asarray(run(i2, q0, k0, v0)))
            d2 = time.perf_counter() - t0
            marg.append((d2 - d1) / (i2 - i1))
        marg = [m for m in marg if m > 0]
        if len(marg) < 2:
            raise RuntimeError("non-positive marginals; noise swamped the "
                               "measurement — rerun on a quieter chip")
        import statistics
        return statistics.median(marg)

    # Two segment scales: near-parity at S=2048 (the chunked inner's
    # working set is still cache-friendly), Pallas ~3.75x ahead at the
    # ring-realistic S=4096 (the f32 [B,H,S,chunk] slabs leave VMEM) —
    # the measurement behind routing TPU rings through the Pallas path.
    for S in (2048, 4096):
        fl = 4 * B * H * S * S * D // 2 * 3  # causal diag fwd + 2x bwd
        res = {"pallas": marginal(S, ra._seg_fwd_pallas, ra._seg_bwd_pallas),
               "jax_chunked": marginal(S, ra._seg_fwd_jax, ra._seg_bwd_jax)}
        print(json.dumps({
            "bench": "ring_segment_fwd_bwd",
            "shape": f"B{B} H{H} S{S} D{D} diag",
            **{f"{k}_ms": round(v * 1e3, 2) for k, v in res.items()},
            **{f"{k}_tflops": round(fl / v / 1e12, 1)
               for k, v in res.items()},
            "pallas_speedup": round(res["jax_chunked"] / res["pallas"], 2),
        }))


if __name__ == "__main__":
    main()
