"""Cluster health report over the root KV server's observability
endpoints (ISSUE 18 — the operator's one-stop view of the hierarchical
telemetry fabric).

Input: the root server's base URL (``--url http://host:port``, the same
server that serves the merged ``GET /metrics`` / ``GET /trace``). The
report pulls three endpoints:

- ``GET /agg`` — aggregator registrations, per-stream rollup freshness,
  and the server's own per-(verb, scope) request accounting;
- ``GET /metrics`` — the merged Prometheus scrape (fallback / shed /
  failover counters, per-rank step counts);
- ``GET /trace`` — the merged cluster trace (straggler ranking via
  ``tools/trace_report.py`` analysis).

Sections (``python tools/health_report.py --url http://host:port``):

- **per-slice telemetry freshness** — each slice's aggregator address
  and the age of its last ``metrics``/``trace``/``stall`` rollup (a
  slice whose rollups stopped aging forward is a dead or wedged
  aggregator; ranks then show up in the fallback counts instead);
- **stragglers** — the trace analyzer's last-arrival ranking;
- **degradation counters** — aggregator fallbacks
  (``hvd_tpu_agg_fallback_total``), shed telemetry bytes
  (``hvd_tpu_kv_shed_bytes_total``), KV failovers/breaker trips, lost
  acked writes — every way the control plane degrades, with the
  convention that nonzero is worth a look and zero is healthy;
- **control-plane load** — ``hvd_tpu_kv_requests_total`` by verb and
  scope plus requests-per-step (total KV requests over total cluster
  steps): the number the aggregator tier exists to keep O(slices);
- **driver replication** (ISSUE 19) — the elastic driver's journal head
  (``GET /driver/head``), the KV replica role/epoch and standby apply
  lag (``GET /_repl/status``), and the promotion/failover counters
  (``hvd_tpu_driver_{journal_writes,promotions,failovers}_total``,
  ``hvd_tpu_elastic_recoveries_total{kind="driver_failover"}``) — the
  at-a-glance answer to "could a standby take over right now, and has
  one ever had to?";
- **step health / SLO** (ISSUE 20) — cluster p50/p99 step time from the
  merged ``hvd_tpu_step_seconds`` histogram, the anomaly inventory by
  class and rank (``hvd_tpu_step_anomalies_total``), flight dumps by
  trigger, and per-rank HBM headroom (``hvd_tpu_hbm_bytes``).

``--json`` emits the assembled report as one JSON object.
``--format=json`` instead emits the *evaluated* report in the
``tools/check.py`` shape — ``{"ok": bool, "checks": {section:
{"ok", "errors", "stats"}}}`` — and the process exits nonzero when any
section is red, so CI and chaos jobs can assert on cluster health
machine-readably.
"""

from __future__ import annotations

import json
import os
import re
import sys
import time
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

_SERIES_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>[^\s]+)\s*$')
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def parse_prometheus(text: str) -> Dict[str, List[Tuple[dict, float]]]:
    """Parse a Prometheus text exposition into
    ``name -> [(labels, value)]``. Tolerant: unparseable lines are
    skipped (the report must work against future scrapes)."""
    out: Dict[str, List[Tuple[dict, float]]] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SERIES_RE.match(line)
        if m is None:
            continue
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        labels = {k: v for k, v in _LABEL_RE.findall(m.group("labels") or "")}
        out.setdefault(m.group("name"), []).append((labels, value))
    return out


def _fetch(url: str, timeout: float = 10.0) -> bytes:
    import urllib.request
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


def _total(series: Dict[str, list], name: str, **match) -> float:
    tot = 0.0
    for labels, v in series.get(name, []):
        if all(labels.get(k) == str(want) for k, want in match.items()):
            tot += v
    return tot


def _by_label(series: Dict[str, list], name: str, label: str
              ) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for labels, v in series.get(name, []):
        key = labels.get(label, "")
        out[key] = out.get(key, 0.0) + v
    return out


def histogram_quantile(series: Dict[str, list], name: str,
                       q: float) -> Optional[float]:
    """Quantile estimate from merged Prometheus histogram ``_bucket``
    series (the bucket upper bound the q-th observation falls in —
    log2 buckets, so the estimate is within 2x). Cumulative counts are
    summed across every rank's series per ``le`` bound."""
    by_le: Dict[float, float] = {}
    for labels, v in series.get(name + "_bucket", []):
        le = labels.get("le", "")
        bound = float("inf") if le in ("+Inf", "inf") else float(le)
        by_le[bound] = by_le.get(bound, 0.0) + v
    if not by_le:
        return None
    total = by_le.get(float("inf"), max(by_le.values()))
    if total <= 0:
        return None
    target = q * total
    for bound in sorted(by_le):
        if by_le[bound] >= target:
            return bound
    return float("inf")


# ---------------------------------------------------------------------------
# report assembly
# ---------------------------------------------------------------------------

def slice_freshness(agg_summary: dict, now: Optional[float] = None) -> dict:
    """Per-slice aggregator registration + rollup ages in seconds:
    ``slice -> {addr, ranks, rollup_age: {stream: seconds|None}}``."""
    if now is None:
        now = time.time()
    slices = agg_summary.get("slices", {}) or {}
    rollups = agg_summary.get("rollups", {}) or {}
    out: Dict[str, dict] = {}
    for k, reg in sorted(slices.items(), key=lambda kv: str(kv[0])):
        reg = reg if isinstance(reg, dict) else {}
        ent = {"addr": reg.get("addr"), "ranks": reg.get("ranks"),
               "rollup_age": {}}
        for stream, per_slice in rollups.items():
            roll = (per_slice or {}).get(str(k))
            ts = roll.get("ts") if isinstance(roll, dict) else None
            ent["rollup_age"][stream] = (
                round(now - float(ts), 1)
                if isinstance(ts, (int, float)) else None)
        out[str(k)] = ent
    return out


def degradation_counters(series: Dict[str, list]) -> dict:
    """Every counter that records a control-plane degradation, totalled
    (and split by stream/scope where the labels carry attribution).
    Zero everywhere = healthy."""
    return {
        "agg_fallbacks": {
            "total": _total(series, "hvd_tpu_agg_fallback_total"),
            "by_stream": _by_label(series, "hvd_tpu_agg_fallback_total",
                                   "stream")},
        "shed_bytes": {
            "total": _total(series, "hvd_tpu_kv_shed_bytes_total"),
            "by_scope": _by_label(series, "hvd_tpu_kv_shed_bytes_total",
                                  "scope")},
        "kv_failovers": _total(series, "hvd_tpu_kv_failover_total"),
        "kv_breaker_trips": _total(series, "hvd_tpu_kv_breaker_open_total"),
        "kv_backpressure": _total(series, "hvd_tpu_kv_backpressure_total"),
        "kv_gave_up": _total(series, "hvd_tpu_kv_gave_up_total"),
        "kv_acked_writes_lost": _total(
            series, "hvd_tpu_kv_acked_writes_lost_total"),
        "watchdog_escalations": _total(
            series, "hvd_tpu_watchdog_escalations_total"),
        "stall_publish_failures": _total(
            series, "hvd_tpu_stall_publish_failures_total"),
        "trace_publish_failures": _total(
            series, "hvd_tpu_trace_publish_failures_total"),
    }


def control_plane_load(series: Dict[str, list],
                       agg_summary: Optional[dict] = None) -> dict:
    """KV request volume at the root by verb and scope, normalized per
    cluster step — the O(slices)-vs-O(ranks) headline number."""
    requests = _by_label(series, "hvd_tpu_kv_requests_total", "scope")
    req_bytes = _by_label(series, "hvd_tpu_kv_request_bytes_total", "scope")
    by_verb = _by_label(series, "hvd_tpu_kv_requests_total", "verb")
    steps_by_rank = {
        labels.get("rank", ""): v
        for labels, v in series.get("hvd_tpu_steps_total", [])
        if labels.get("rank", "") not in ("", "driver")}
    total_steps = max(steps_by_rank.values()) if steps_by_rank else 0.0
    total_requests = sum(requests.values())
    out = {
        "requests_by_scope": requests,
        "request_bytes_by_scope": req_bytes,
        "requests_by_verb": by_verb,
        "total_requests": total_requests,
        "cluster_steps": total_steps,
        "steps_by_rank": steps_by_rank,
        "requests_per_step": (
            round(total_requests / total_steps, 2)
            if total_steps > 0 else None),
    }
    if agg_summary:
        out["server_request_stats"] = agg_summary.get("request_stats", {})
    return out


def driver_replication(series: Dict[str, list],
                       repl_status: Optional[dict],
                       journal_head: Optional[int]) -> dict:
    """Driver fault-domain health (ISSUE 19): journal head, replica
    role/epoch, standby apply lag, and the promotion/failover history.
    ``journal_head is None`` means no elastic driver has journaled yet
    (non-elastic job, or journaling disabled)."""
    st = repl_status or {}
    seq = st.get("seq")
    applied = st.get("applied_seq")
    lag = (max(0, int(seq) - int(applied))
           if isinstance(seq, (int, float)) and
           isinstance(applied, (int, float)) else None)
    return {
        "journal_head": journal_head,
        "repl_role": st.get("role"),
        "repl_epoch": st.get("epoch"),
        "standby_lag": lag,
        "journal_writes": {
            "total": _total(series, "hvd_tpu_driver_journal_writes_total"),
            "by_kind": _by_label(
                series, "hvd_tpu_driver_journal_writes_total", "kind")},
        "promotions": _total(series, "hvd_tpu_driver_promotions_total"),
        "failovers": _total(series, "hvd_tpu_driver_failovers_total"),
        "failover_recoveries": _total(
            series, "hvd_tpu_elastic_recoveries_total",
            kind="driver_failover"),
        "discovery_failures": _total(
            series, "hvd_tpu_discovery_failures_total"),
    }


def step_health(series: Dict[str, list]) -> dict:
    """Step health / SLO (ISSUE 20): cluster step-time percentiles from
    the merged ``hvd_tpu_step_seconds`` histogram, the anomaly
    inventory by class and rank, flight dumps by trigger, and per-rank
    HBM headroom."""
    count = _total(series, "hvd_tpu_step_seconds_count")
    ssum = _total(series, "hvd_tpu_step_seconds_sum")
    p50 = histogram_quantile(series, "hvd_tpu_step_seconds", 0.50)
    p99 = histogram_quantile(series, "hvd_tpu_step_seconds", 0.99)
    anomalies_by_rank: Dict[str, Dict[str, float]] = {}
    for labels, v in series.get("hvd_tpu_step_anomalies_total", []):
        rank = labels.get("rank", "")
        cls = labels.get("class", "")
        anomalies_by_rank.setdefault(rank, {})
        anomalies_by_rank[rank][cls] = \
            anomalies_by_rank[rank].get(cls, 0.0) + v
    hbm: Dict[str, dict] = {}
    for labels, v in series.get("hvd_tpu_hbm_bytes", []):
        rank = labels.get("rank", "")
        hbm.setdefault(rank, {})[labels.get("kind", "")] = v
    headroom = {}
    for rank, kinds in hbm.items():
        limit, in_use = kinds.get("limit"), kinds.get("in_use")
        if limit and in_use is not None:
            headroom[rank] = limit - in_use
    return {
        "steps_observed": count,
        "step_time_mean_ms": (
            round(1e3 * ssum / count, 3) if count else None),
        "step_time_p50_ms": (
            round(1e3 * p50, 3) if p50 not in (None, float("inf"))
            else None),
        "step_time_p99_ms": (
            round(1e3 * p99, 3) if p99 not in (None, float("inf"))
            else None),
        "anomalies_total": _total(
            series, "hvd_tpu_step_anomalies_total"),
        "anomalies_by_class": _by_label(
            series, "hvd_tpu_step_anomalies_total", "class"),
        "anomalies_by_rank": anomalies_by_rank,
        "flight_dumps": {
            "total": _total(series, "hvd_tpu_flight_dumps_total"),
            "by_trigger": _by_label(
                series, "hvd_tpu_flight_dumps_total", "trigger")},
        "hbm_bytes": hbm,
        "hbm_headroom_bytes": headroom,
        "hbm_min_headroom_bytes": (
            min(headroom.values()) if headroom else None),
    }


def assemble(url: str, timeout: float = 10.0) -> dict:
    """Fetch all three endpoints and assemble the report dict. Each
    endpoint degrades independently — a root without the /agg route (flat
    topology, older server) still yields the metrics/trace sections."""
    report: dict = {"url": url, "ts": time.time(), "errors": {}}
    agg_summary: dict = {}
    try:
        agg_summary = json.loads(_fetch(url.rstrip("/") + "/agg", timeout))
    except Exception as e:
        report["errors"]["agg"] = str(e)
    series: Dict[str, list] = {}
    try:
        series = parse_prometheus(
            _fetch(url.rstrip("/") + "/metrics", timeout).decode(
                "utf-8", "replace"))
    except Exception as e:
        report["errors"]["metrics"] = str(e)
    # Optional subsystems: a 404 just means "not replicated" / "no
    # elastic driver journaling yet", not an unhealthy endpoint.
    repl_status: Optional[dict] = None
    try:
        repl_status = json.loads(
            _fetch(url.rstrip("/") + "/_repl/status", timeout))
    except Exception:
        pass
    journal_head: Optional[int] = None
    try:
        journal_head = int(
            _fetch(url.rstrip("/") + "/driver/head", timeout))
    except Exception:
        pass
    report["slices"] = slice_freshness(agg_summary)
    report["degradation"] = degradation_counters(series)
    report["control_plane"] = control_plane_load(series, agg_summary)
    report["driver_replication"] = driver_replication(
        series, repl_status, journal_head)
    report["step_health"] = step_health(series)
    try:
        from horovod_tpu.trace import load_trace_events
        from tools.trace_report import arrival_skew, straggler_ranking
        events = load_trace_events(
            _fetch(url.rstrip("/") + "/trace", timeout).decode(
                "utf-8", "replace"))
        ranking = straggler_ranking(arrival_skew(events))
        report["stragglers"] = ranking[:5]
        report["trace_events"] = len(events)
    except Exception as e:
        report["errors"]["trace"] = str(e)
        report["stragglers"] = []
    return report


def evaluate(report: dict, stale_after: float = 120.0) -> dict:
    """Red/green the assembled report per section, in the
    ``tools/check.py`` shape: ``{"ok", "checks": {section: {"ok",
    "errors", "stats"}}}``. Green everywhere is the steady healthy
    state; every red line names the evidence."""
    checks: Dict[str, dict] = {}

    def add(name: str, errors: List[str], stats: dict):
        checks[name] = {"ok": not errors, "errors": errors, "stats": stats}

    errs = []
    if "metrics" in report.get("errors", {}):
        errs.append("metrics endpoint unavailable: "
                    f"{report['errors']['metrics']}")
    add("endpoints", errs, {"errors": report.get("errors", {})})

    errs = []
    for k, ent in report.get("slices", {}).items():
        for stream, age in ent.get("rollup_age", {}).items():
            if age is not None and age > stale_after:
                errs.append(f"slice {k} {stream} rollup is {age:.0f}s "
                            f"stale (> {stale_after:.0f}s)")
    add("slices", errs, {"slices": len(report.get("slices", {}))})

    deg = report.get("degradation", {})
    errs = []
    for key, label in (("kv_acked_writes_lost", "acked KV writes lost"),
                       ("kv_gave_up", "KV publishes gave up"),
                       ("watchdog_escalations", "watchdog escalations")):
        if deg.get(key, 0):
            errs.append(f"{label}: {deg[key]:.0f}")
    add("degradation", errs, deg)

    sh = report.get("step_health", {})
    errs = []
    if sh.get("anomalies_total", 0):
        by_cls = ", ".join(f"{c}={v:.0f}" for c, v in
                           sorted(sh.get("anomalies_by_class", {}).items()))
        errs.append(f"{sh['anomalies_total']:.0f} step anomalies "
                    f"({by_cls})")
    for rank, hr in sorted(sh.get("hbm_headroom_bytes", {}).items()):
        if hr < 0:
            errs.append(f"rank {rank} HBM over limit by {-hr:.0f} bytes")
    add("step_health", errs, sh)

    add("control_plane", [], report.get("control_plane", {}))
    add("driver_replication", [], report.get("driver_replication", {}))

    return {"ok": all(c["ok"] for c in checks.values()), "checks": checks}


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _fmt_age(age) -> str:
    return "never" if age is None else f"{age:.1f}s ago"


def render(report: dict) -> str:
    lines = [f"cluster health @ {report['url']}"]
    for endpoint, err in sorted(report.get("errors", {}).items()):
        lines.append(f"  !! {endpoint} endpoint unavailable: {err}")
    slices = report.get("slices", {})
    lines.append("")
    if slices:
        lines.append("per-slice telemetry freshness:")
        for k, ent in slices.items():
            ages = "  ".join(
                f"{s}={_fmt_age(a)}"
                for s, a in sorted(ent["rollup_age"].items()))
            lines.append(f"  slice {k:<3} agg={ent['addr']}  "
                         f"ranks={ent['ranks']}  {ages}")
    else:
        lines.append("per-slice telemetry: no aggregators registered "
                     "(flat topology or HOROVOD_TPU_AGG_ENABLE=0) — "
                     "publishes go direct to the root")
    stragglers = report.get("stragglers", [])
    lines.append("")
    if stragglers:
        lines.append("top stragglers (last arrival at correlated "
                     "collectives):")
        for acc in stragglers:
            lines.append(f"  rank {acc['rank']:<4} "
                         f"last {acc['last_count']}x  "
                         f"mean lateness {acc['mean_late_us']:.0f} us")
    else:
        lines.append("stragglers: none detected")
    deg = report.get("degradation", {})
    lines.append("")
    lines.append("degradation counters (zero = healthy):")
    fb = deg.get("agg_fallbacks", {})
    by_stream = " ".join(f"{s}={v:.0f}" for s, v
                         in sorted(fb.get("by_stream", {}).items()))
    lines.append(f"  aggregator fallbacks: {fb.get('total', 0):.0f}"
                 + (f"  ({by_stream})" if by_stream else ""))
    shed = deg.get("shed_bytes", {})
    lines.append(f"  shed telemetry bytes: {shed.get('total', 0):.0f}")
    for key, label in (("kv_failovers", "kv failovers"),
                       ("kv_breaker_trips", "kv breaker trips"),
                       ("kv_backpressure", "kv backpressure hits"),
                       ("kv_gave_up", "kv gave-up publishes"),
                       ("kv_acked_writes_lost", "acked writes lost"),
                       ("watchdog_escalations", "watchdog escalations")):
        lines.append(f"  {label}: {deg.get(key, 0):.0f}")
    cp = report.get("control_plane", {})
    lines.append("")
    lines.append("control-plane load at the root:")
    per_step = cp.get("requests_per_step")
    lines.append(f"  kv requests: {cp.get('total_requests', 0):.0f} total"
                 + (f", {per_step} per step" if per_step is not None
                    else " (no steps recorded yet)"))
    scopes = cp.get("requests_by_scope", {})
    if scopes:
        row = "  ".join(f"{s}={v:.0f}" for s, v in sorted(scopes.items()))
        lines.append(f"  by scope: {row}")
    verbs = cp.get("requests_by_verb", {})
    if verbs:
        row = "  ".join(f"{v}={n:.0f}" for v, n in sorted(verbs.items()))
        lines.append(f"  by verb: {row}")
    dr = report.get("driver_replication", {})
    lines.append("")
    lines.append("driver replication:")
    head = dr.get("journal_head")
    if head is None:
        lines.append("  journal: no driver journal at this server "
                     "(non-elastic job, or HOROVOD_TPU_DRIVER_JOURNAL=0)")
    else:
        jw = dr.get("journal_writes", {})
        by_kind = " ".join(f"{k}={v:.0f}" for k, v
                           in sorted(jw.get("by_kind", {}).items()))
        lines.append(f"  journal head: seq {head}"
                     + (f"  ({by_kind})" if by_kind else ""))
    role = dr.get("repl_role")
    if role is None:
        lines.append("  kv replication: not enabled at this server")
    else:
        lag = dr.get("standby_lag")
        lines.append(
            f"  kv replica: role={role} epoch={dr.get('repl_epoch')}  "
            f"standby lag={'?' if lag is None else f'{lag} entries'}")
    lines.append(
        f"  promotions: {dr.get('promotions', 0):.0f}  "
        f"failovers: {dr.get('failovers', 0):.0f}  "
        f"failover recoveries: {dr.get('failover_recoveries', 0):.0f}  "
        f"discovery failures: {dr.get('discovery_failures', 0):.0f}")
    sh = report.get("step_health", {})
    lines.append("")
    lines.append("step health / SLO:")
    if sh.get("steps_observed"):
        def _ms(v):
            return "?" if v is None else f"{v:.1f} ms"
        lines.append(
            f"  step time: p50 {_ms(sh.get('step_time_p50_ms'))}  "
            f"p99 {_ms(sh.get('step_time_p99_ms'))}  "
            f"mean {_ms(sh.get('step_time_mean_ms'))}  "
            f"({sh['steps_observed']:.0f} steps observed)")
    else:
        lines.append("  step time: no hvd_tpu_step_seconds samples yet "
                     "(HOROVOD_TPU_STEP_HEALTH=0, or no steps bracketed)")
    anom = sh.get("anomalies_total", 0)
    if anom:
        by_cls = "  ".join(
            f"{c}={v:.0f}" for c, v in
            sorted(sh.get("anomalies_by_class", {}).items()))
        lines.append(f"  anomalies: {anom:.0f}  ({by_cls})")
        for rank, classes in sorted(sh.get("anomalies_by_rank",
                                           {}).items()):
            row = "  ".join(f"{c}={v:.0f}"
                            for c, v in sorted(classes.items()))
            lines.append(f"    rank {rank:<4} {row}")
    else:
        lines.append("  anomalies: none")
    dumps = sh.get("flight_dumps", {})
    if dumps.get("total"):
        by_trig = "  ".join(
            f"{t}={v:.0f}" for t, v in
            sorted(dumps.get("by_trigger", {}).items()))
        lines.append(f"  flight dumps: {dumps['total']:.0f}  ({by_trig})")
    headroom = sh.get("hbm_headroom_bytes", {})
    if headroom:
        for rank, hr in sorted(headroom.items()):
            kinds = sh.get("hbm_bytes", {}).get(rank, {})
            lines.append(
                f"  rank {rank:<4} HBM headroom {hr / 2**30:.2f} GiB "
                f"(in use {kinds.get('in_use', 0) / 2**30:.2f} / "
                f"limit {kinds.get('limit', 0) / 2**30:.2f} GiB)")
    else:
        lines.append("  hbm: no device memory stats published "
                     "(CPU rig, or HOROVOD_TPU_HBM=0)")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    p = argparse.ArgumentParser(
        description="Cluster health report over the root KV server's "
                    "/agg, /metrics and /trace endpoints")
    p.add_argument("--url", required=True,
                   help="root server base URL, e.g. http://host:port")
    p.add_argument("--timeout", type=float, default=10.0,
                   help="per-endpoint fetch timeout (seconds)")
    p.add_argument("--json", action="store_true",
                   help="emit the raw assembled report as JSON")
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="'json' emits the evaluated report in the "
                        "tools/check.py shape ({ok, checks}); the exit "
                        "code is nonzero when any section is red")
    args = p.parse_args(argv)
    report = assemble(args.url, timeout=args.timeout)
    verdict = evaluate(report)
    if args.format == "json":
        print(json.dumps(verdict, indent=2, sort_keys=True))
    elif args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(render(report))
        if not verdict["ok"]:
            red = [name for name, c in sorted(verdict["checks"].items())
                   if not c["ok"]]
            print(f"\nRED sections: {', '.join(red)}")
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
