"""Cluster health report over the root KV server's observability
endpoints (ISSUE 18 — the operator's one-stop view of the hierarchical
telemetry fabric).

Input: the root server's base URL (``--url http://host:port``, the same
server that serves the merged ``GET /metrics`` / ``GET /trace``). The
report pulls three endpoints:

- ``GET /agg`` — aggregator registrations, per-stream rollup freshness,
  and the server's own per-(verb, scope) request accounting;
- ``GET /metrics`` — the merged Prometheus scrape (fallback / shed /
  failover counters, per-rank step counts);
- ``GET /trace`` — the merged cluster trace (straggler ranking via
  ``tools/trace_report.py`` analysis).

Sections (``python tools/health_report.py --url http://host:port``):

- **per-slice telemetry freshness** — each slice's aggregator address
  and the age of its last ``metrics``/``trace``/``stall`` rollup (a
  slice whose rollups stopped aging forward is a dead or wedged
  aggregator; ranks then show up in the fallback counts instead);
- **stragglers** — the trace analyzer's last-arrival ranking;
- **degradation counters** — aggregator fallbacks
  (``hvd_tpu_agg_fallback_total``), shed telemetry bytes
  (``hvd_tpu_kv_shed_bytes_total``), KV failovers/breaker trips, lost
  acked writes — every way the control plane degrades, with the
  convention that nonzero is worth a look and zero is healthy;
- **control-plane load** — ``hvd_tpu_kv_requests_total`` by verb and
  scope plus requests-per-step (total KV requests over total cluster
  steps): the number the aggregator tier exists to keep O(slices);
- **driver replication** (ISSUE 19) — the elastic driver's journal head
  (``GET /driver/head``), the KV replica role/epoch and standby apply
  lag (``GET /_repl/status``), and the promotion/failover counters
  (``hvd_tpu_driver_{journal_writes,promotions,failovers}_total``,
  ``hvd_tpu_elastic_recoveries_total{kind="driver_failover"}``) — the
  at-a-glance answer to "could a standby take over right now, and has
  one ever had to?".

``--json`` emits the assembled report as one JSON object instead.
"""

from __future__ import annotations

import json
import os
import re
import sys
import time
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

_SERIES_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>[^\s]+)\s*$')
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def parse_prometheus(text: str) -> Dict[str, List[Tuple[dict, float]]]:
    """Parse a Prometheus text exposition into
    ``name -> [(labels, value)]``. Tolerant: unparseable lines are
    skipped (the report must work against future scrapes)."""
    out: Dict[str, List[Tuple[dict, float]]] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SERIES_RE.match(line)
        if m is None:
            continue
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        labels = {k: v for k, v in _LABEL_RE.findall(m.group("labels") or "")}
        out.setdefault(m.group("name"), []).append((labels, value))
    return out


def _fetch(url: str, timeout: float = 10.0) -> bytes:
    import urllib.request
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


def _total(series: Dict[str, list], name: str, **match) -> float:
    tot = 0.0
    for labels, v in series.get(name, []):
        if all(labels.get(k) == str(want) for k, want in match.items()):
            tot += v
    return tot


def _by_label(series: Dict[str, list], name: str, label: str
              ) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for labels, v in series.get(name, []):
        key = labels.get(label, "")
        out[key] = out.get(key, 0.0) + v
    return out


# ---------------------------------------------------------------------------
# report assembly
# ---------------------------------------------------------------------------

def slice_freshness(agg_summary: dict, now: Optional[float] = None) -> dict:
    """Per-slice aggregator registration + rollup ages in seconds:
    ``slice -> {addr, ranks, rollup_age: {stream: seconds|None}}``."""
    if now is None:
        now = time.time()
    slices = agg_summary.get("slices", {}) or {}
    rollups = agg_summary.get("rollups", {}) or {}
    out: Dict[str, dict] = {}
    for k, reg in sorted(slices.items(), key=lambda kv: str(kv[0])):
        reg = reg if isinstance(reg, dict) else {}
        ent = {"addr": reg.get("addr"), "ranks": reg.get("ranks"),
               "rollup_age": {}}
        for stream, per_slice in rollups.items():
            roll = (per_slice or {}).get(str(k))
            ts = roll.get("ts") if isinstance(roll, dict) else None
            ent["rollup_age"][stream] = (
                round(now - float(ts), 1)
                if isinstance(ts, (int, float)) else None)
        out[str(k)] = ent
    return out


def degradation_counters(series: Dict[str, list]) -> dict:
    """Every counter that records a control-plane degradation, totalled
    (and split by stream/scope where the labels carry attribution).
    Zero everywhere = healthy."""
    return {
        "agg_fallbacks": {
            "total": _total(series, "hvd_tpu_agg_fallback_total"),
            "by_stream": _by_label(series, "hvd_tpu_agg_fallback_total",
                                   "stream")},
        "shed_bytes": {
            "total": _total(series, "hvd_tpu_kv_shed_bytes_total"),
            "by_scope": _by_label(series, "hvd_tpu_kv_shed_bytes_total",
                                  "scope")},
        "kv_failovers": _total(series, "hvd_tpu_kv_failover_total"),
        "kv_breaker_trips": _total(series, "hvd_tpu_kv_breaker_open_total"),
        "kv_backpressure": _total(series, "hvd_tpu_kv_backpressure_total"),
        "kv_gave_up": _total(series, "hvd_tpu_kv_gave_up_total"),
        "kv_acked_writes_lost": _total(
            series, "hvd_tpu_kv_acked_writes_lost_total"),
        "watchdog_escalations": _total(
            series, "hvd_tpu_watchdog_escalations_total"),
        "stall_publish_failures": _total(
            series, "hvd_tpu_stall_publish_failures_total"),
        "trace_publish_failures": _total(
            series, "hvd_tpu_trace_publish_failures_total"),
    }


def control_plane_load(series: Dict[str, list],
                       agg_summary: Optional[dict] = None) -> dict:
    """KV request volume at the root by verb and scope, normalized per
    cluster step — the O(slices)-vs-O(ranks) headline number."""
    requests = _by_label(series, "hvd_tpu_kv_requests_total", "scope")
    req_bytes = _by_label(series, "hvd_tpu_kv_request_bytes_total", "scope")
    by_verb = _by_label(series, "hvd_tpu_kv_requests_total", "verb")
    steps_by_rank = {
        labels.get("rank", ""): v
        for labels, v in series.get("hvd_tpu_steps_total", [])
        if labels.get("rank", "") not in ("", "driver")}
    total_steps = max(steps_by_rank.values()) if steps_by_rank else 0.0
    total_requests = sum(requests.values())
    out = {
        "requests_by_scope": requests,
        "request_bytes_by_scope": req_bytes,
        "requests_by_verb": by_verb,
        "total_requests": total_requests,
        "cluster_steps": total_steps,
        "steps_by_rank": steps_by_rank,
        "requests_per_step": (
            round(total_requests / total_steps, 2)
            if total_steps > 0 else None),
    }
    if agg_summary:
        out["server_request_stats"] = agg_summary.get("request_stats", {})
    return out


def driver_replication(series: Dict[str, list],
                       repl_status: Optional[dict],
                       journal_head: Optional[int]) -> dict:
    """Driver fault-domain health (ISSUE 19): journal head, replica
    role/epoch, standby apply lag, and the promotion/failover history.
    ``journal_head is None`` means no elastic driver has journaled yet
    (non-elastic job, or journaling disabled)."""
    st = repl_status or {}
    seq = st.get("seq")
    applied = st.get("applied_seq")
    lag = (max(0, int(seq) - int(applied))
           if isinstance(seq, (int, float)) and
           isinstance(applied, (int, float)) else None)
    return {
        "journal_head": journal_head,
        "repl_role": st.get("role"),
        "repl_epoch": st.get("epoch"),
        "standby_lag": lag,
        "journal_writes": {
            "total": _total(series, "hvd_tpu_driver_journal_writes_total"),
            "by_kind": _by_label(
                series, "hvd_tpu_driver_journal_writes_total", "kind")},
        "promotions": _total(series, "hvd_tpu_driver_promotions_total"),
        "failovers": _total(series, "hvd_tpu_driver_failovers_total"),
        "failover_recoveries": _total(
            series, "hvd_tpu_elastic_recoveries_total",
            kind="driver_failover"),
        "discovery_failures": _total(
            series, "hvd_tpu_discovery_failures_total"),
    }


def assemble(url: str, timeout: float = 10.0) -> dict:
    """Fetch all three endpoints and assemble the report dict. Each
    endpoint degrades independently — a root without the /agg route (flat
    topology, older server) still yields the metrics/trace sections."""
    report: dict = {"url": url, "ts": time.time(), "errors": {}}
    agg_summary: dict = {}
    try:
        agg_summary = json.loads(_fetch(url.rstrip("/") + "/agg", timeout))
    except Exception as e:
        report["errors"]["agg"] = str(e)
    series: Dict[str, list] = {}
    try:
        series = parse_prometheus(
            _fetch(url.rstrip("/") + "/metrics", timeout).decode(
                "utf-8", "replace"))
    except Exception as e:
        report["errors"]["metrics"] = str(e)
    # Optional subsystems: a 404 just means "not replicated" / "no
    # elastic driver journaling yet", not an unhealthy endpoint.
    repl_status: Optional[dict] = None
    try:
        repl_status = json.loads(
            _fetch(url.rstrip("/") + "/_repl/status", timeout))
    except Exception:
        pass
    journal_head: Optional[int] = None
    try:
        journal_head = int(
            _fetch(url.rstrip("/") + "/driver/head", timeout))
    except Exception:
        pass
    report["slices"] = slice_freshness(agg_summary)
    report["degradation"] = degradation_counters(series)
    report["control_plane"] = control_plane_load(series, agg_summary)
    report["driver_replication"] = driver_replication(
        series, repl_status, journal_head)
    try:
        from horovod_tpu.trace import load_trace_events
        from tools.trace_report import arrival_skew, straggler_ranking
        events = load_trace_events(
            _fetch(url.rstrip("/") + "/trace", timeout).decode(
                "utf-8", "replace"))
        ranking = straggler_ranking(arrival_skew(events))
        report["stragglers"] = ranking[:5]
        report["trace_events"] = len(events)
    except Exception as e:
        report["errors"]["trace"] = str(e)
        report["stragglers"] = []
    return report


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _fmt_age(age) -> str:
    return "never" if age is None else f"{age:.1f}s ago"


def render(report: dict) -> str:
    lines = [f"cluster health @ {report['url']}"]
    for endpoint, err in sorted(report.get("errors", {}).items()):
        lines.append(f"  !! {endpoint} endpoint unavailable: {err}")
    slices = report.get("slices", {})
    lines.append("")
    if slices:
        lines.append("per-slice telemetry freshness:")
        for k, ent in slices.items():
            ages = "  ".join(
                f"{s}={_fmt_age(a)}"
                for s, a in sorted(ent["rollup_age"].items()))
            lines.append(f"  slice {k:<3} agg={ent['addr']}  "
                         f"ranks={ent['ranks']}  {ages}")
    else:
        lines.append("per-slice telemetry: no aggregators registered "
                     "(flat topology or HOROVOD_TPU_AGG_ENABLE=0) — "
                     "publishes go direct to the root")
    stragglers = report.get("stragglers", [])
    lines.append("")
    if stragglers:
        lines.append("top stragglers (last arrival at correlated "
                     "collectives):")
        for acc in stragglers:
            lines.append(f"  rank {acc['rank']:<4} "
                         f"last {acc['last_count']}x  "
                         f"mean lateness {acc['mean_late_us']:.0f} us")
    else:
        lines.append("stragglers: none detected")
    deg = report.get("degradation", {})
    lines.append("")
    lines.append("degradation counters (zero = healthy):")
    fb = deg.get("agg_fallbacks", {})
    by_stream = " ".join(f"{s}={v:.0f}" for s, v
                         in sorted(fb.get("by_stream", {}).items()))
    lines.append(f"  aggregator fallbacks: {fb.get('total', 0):.0f}"
                 + (f"  ({by_stream})" if by_stream else ""))
    shed = deg.get("shed_bytes", {})
    lines.append(f"  shed telemetry bytes: {shed.get('total', 0):.0f}")
    for key, label in (("kv_failovers", "kv failovers"),
                       ("kv_breaker_trips", "kv breaker trips"),
                       ("kv_backpressure", "kv backpressure hits"),
                       ("kv_gave_up", "kv gave-up publishes"),
                       ("kv_acked_writes_lost", "acked writes lost"),
                       ("watchdog_escalations", "watchdog escalations")):
        lines.append(f"  {label}: {deg.get(key, 0):.0f}")
    cp = report.get("control_plane", {})
    lines.append("")
    lines.append("control-plane load at the root:")
    per_step = cp.get("requests_per_step")
    lines.append(f"  kv requests: {cp.get('total_requests', 0):.0f} total"
                 + (f", {per_step} per step" if per_step is not None
                    else " (no steps recorded yet)"))
    scopes = cp.get("requests_by_scope", {})
    if scopes:
        row = "  ".join(f"{s}={v:.0f}" for s, v in sorted(scopes.items()))
        lines.append(f"  by scope: {row}")
    verbs = cp.get("requests_by_verb", {})
    if verbs:
        row = "  ".join(f"{v}={n:.0f}" for v, n in sorted(verbs.items()))
        lines.append(f"  by verb: {row}")
    dr = report.get("driver_replication", {})
    lines.append("")
    lines.append("driver replication:")
    head = dr.get("journal_head")
    if head is None:
        lines.append("  journal: no driver journal at this server "
                     "(non-elastic job, or HOROVOD_TPU_DRIVER_JOURNAL=0)")
    else:
        jw = dr.get("journal_writes", {})
        by_kind = " ".join(f"{k}={v:.0f}" for k, v
                           in sorted(jw.get("by_kind", {}).items()))
        lines.append(f"  journal head: seq {head}"
                     + (f"  ({by_kind})" if by_kind else ""))
    role = dr.get("repl_role")
    if role is None:
        lines.append("  kv replication: not enabled at this server")
    else:
        lag = dr.get("standby_lag")
        lines.append(
            f"  kv replica: role={role} epoch={dr.get('repl_epoch')}  "
            f"standby lag={'?' if lag is None else f'{lag} entries'}")
    lines.append(
        f"  promotions: {dr.get('promotions', 0):.0f}  "
        f"failovers: {dr.get('failovers', 0):.0f}  "
        f"failover recoveries: {dr.get('failover_recoveries', 0):.0f}  "
        f"discovery failures: {dr.get('discovery_failures', 0):.0f}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    p = argparse.ArgumentParser(
        description="Cluster health report over the root KV server's "
                    "/agg, /metrics and /trace endpoints")
    p.add_argument("--url", required=True,
                   help="root server base URL, e.g. http://host:port")
    p.add_argument("--timeout", type=float, default=10.0,
                   help="per-endpoint fetch timeout (seconds)")
    p.add_argument("--json", action="store_true",
                   help="emit the report as JSON")
    args = p.parse_args(argv)
    report = assemble(args.url, timeout=args.timeout)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(render(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
