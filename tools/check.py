"""Unified static-analysis driver: every lint, one command, one report.

Runs the eight analysis passes the repo has accumulated (PRs 3-5 grew
one script per namespace; ISSUE 7 consolidated them and added the
concurrency lints; ISSUE 9 added the checkpoint-manifest contract;
ISSUE 11 added the SPMD divergence checker; ISSUE 15 added the
error-flow analyzer and folded the name lints into
``horovod_tpu/analysis/``):

- ``lockcheck``     — GUARDED_BY lock-discipline checker over
                      ``horovod_tpu/`` (horovod_tpu.analysis.lockcheck)
- ``divcheck``      — SPMD divergence & dispatch-determinism checker:
                      rank-gated collectives, nondeterministic
                      submission order, unagreed selection inputs,
                      capture-impure reads
                      (horovod_tpu.analysis.divcheck)
- ``knobs``         — configuration-knob registry lint: env reads vs
                      KNOB_SPECS, declared choices/types vs defaults,
                      raw reads of choice knobs
                      (horovod_tpu.analysis.knobcheck)
- ``metrics``       — METRIC_SPECS namespace lint
                      (horovod_tpu.analysis.metriccheck)
- ``faults``        — FAULT_SPECS + failpoint call-site lint
                      (horovod_tpu.analysis.faultcheck)
- ``trace_schema``  — trace-schema contract self-check: a synthetic
                      2-rank merged trace must pass
                      ``tools/trace_report.py --check``'s ``check_events``
                      and a deliberately-broken event list must fail it
- ``ckpt_manifest`` — checkpoint-manifest contract self-check: a live
                      round-tripped 2-rank generation must validate and
                      the commit barrier must reject mismatched
                      checksums / stale world_versions / partial
                      generations (horovod_tpu.checkpoint.manifest)
- ``errflow``       — exception-propagation & resource-lifecycle
                      analyzer: swallowed recovery errors on the
                      elastic/dispatch/watchdog path, deadline-less raw
                      transport calls, leak-on-raise resource
                      lifecycles, silent error seams, failpoint drift
                      (horovod_tpu.analysis.errflow)

Usage (from the repo root)::

    python tools/check.py                  # all lints, text report
    python tools/check.py --format=json    # machine-readable report
    python tools/check.py --format=github  # GitHub Actions annotations
    python tools/check.py --only lockcheck,knobs
    python tools/check.py --changed        # fast dev loop: pure-AST
                                           # lints, findings filtered to
                                           # files changed vs main
    python tools/check.py --list

Exit code 0 iff every selected lint passed. The JSON report carries, per
lint, ``ok`` / ``errors`` / ``stats`` — and for lockcheck/divcheck the
full suppression (and agreed-site) lists with reasons, so "zero
unexplained suppressions" is auditable from the report alone. Invoked
from one tier-1 test (tests/test_check.py, ``pytest -m lint``) and the
CI workflow (.github/workflows/lint.yml); the per-lint scripts remain
as thin shims for single-lint runs.

``--changed`` is the dev-loop fast mode: it runs only the pure-AST
lints (lockcheck, divcheck, knobs, errflow — the ones that don't import
jax or run live subsystems), scanning the WHOLE tree so cross-file
passes stay sound, but filtering lockcheck/divcheck/errflow findings to
files changed vs ``main`` (git diff --name-only + working-tree
changes). The full scan stays the tier-1/CI default.
"""

from __future__ import annotations

import json
import os
import re
import sys
from typing import Callable, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
# the sibling single-lint scripts (check_metric_names, trace_report, ...)
# are imported as top-level modules
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

PKG_ROOT = os.path.join(REPO, "horovod_tpu")


def run_lockcheck(changed: Optional[set] = None) -> Tuple[List[str], dict]:
    from horovod_tpu.analysis import lockcheck
    rep = lockcheck.check_package(PKG_ROOT)
    findings = rep.findings
    if changed is not None:
        findings = [f for f in findings if f.file in changed]
    errors = [str(f) for f in findings]
    stats = {"files": rep.files,
             "classes_annotated": rep.classes_annotated,
             "guarded_attrs": rep.guarded_attrs,
             "suppressions": [s.to_dict() for s in rep.suppressions]}
    if changed is not None:
        stats["changed_files"] = len(changed)
    return errors, stats


def run_divcheck(changed: Optional[set] = None) -> Tuple[List[str], dict]:
    """SPMD divergence checker (ISSUE 11). The whole tree is always
    scanned — the collective-issuing set and step-path footprint are
    cross-file — but ``--changed`` filters the *findings* to the files
    being worked on."""
    from horovod_tpu.analysis import divcheck
    rep = divcheck.check_package(PKG_ROOT)
    findings = rep.findings
    if changed is not None:
        findings = [f for f in findings if f.file in changed]
    errors = [str(f) for f in findings]
    stats = {"files": rep.files,
             "defs": rep.defs,
             "issuing_defs": rep.issuing_defs,
             "step_path_defs": rep.step_path_defs,
             "suppressions": [s.to_dict() for s in rep.suppressions],
             "agreed_sites": [a.to_dict() for a in rep.agreed]}
    if changed is not None:
        stats["changed_files"] = len(changed)
    return errors, stats


def run_knobs() -> Tuple[List[str], dict]:
    from horovod_tpu.analysis import knobcheck
    return knobcheck.run(PKG_ROOT)


def run_metrics() -> Tuple[List[str], dict]:
    from horovod_tpu.analysis import metriccheck
    return metriccheck.run(PKG_ROOT)


def run_faults() -> Tuple[List[str], dict]:
    from horovod_tpu.analysis import faultcheck
    return faultcheck.run(PKG_ROOT)


def run_errflow(changed: Optional[set] = None) -> Tuple[List[str], dict]:
    """Exception-propagation & resource-lifecycle analyzer (ISSUE 15).
    The whole tree is always scanned — the recovery footprint and the
    failpoint registry are cross-file — but ``--changed`` filters the
    *findings* to the files being worked on."""
    from horovod_tpu.analysis import errflow
    rep = errflow.check_package(PKG_ROOT)
    findings = rep.findings
    if changed is not None:
        findings = [f for f in findings if f.file in changed]
    errors = [str(f) for f in findings]
    stats = {"files": rep.files,
             "defs": rep.defs,
             "recovery_defs": rep.recovery_defs,
             "handlers": rep.handlers,
             "failpoints_declared": rep.failpoints_declared,
             "failpoint_sites": rep.failpoint_sites,
             "suppressions": [s.to_dict() for s in rep.suppressions],
             "seams": [s.to_dict() for s in rep.seams]}
    if changed is not None:
        stats["changed_files"] = len(changed)
    return errors, stats


def run_trace_schema() -> Tuple[List[str], dict]:
    """Trace-schema contract self-check. The schema lint proper
    (``trace_report.py --check``) validates a trace *file*; this runner
    proves the contract itself holds end to end: events produced by the
    live recorder/merger pass the lint, and the lint still rejects each
    known violation class (so a green run can't mean a gutted checker)."""
    import trace_report
    from horovod_tpu.trace import TraceRecorder, merge_segments
    errors: List[str] = []
    segments = {}
    for rank in (0, 1):
        rec = TraceRecorder(rank=rank, capacity=64)
        rec.add_beacon(0.0, 1000.0, 0.001)
        for step in range(2):
            rec.record_step(begin=True)
            rec.record_enqueue("grad", "allreduce", 1024, 0)
            rec.record_dispatch("grad", "XLA_DISPATCH", 0.001)
            rec.record_done("grad")
            rec.record_step(begin=False)
        segments[rank] = rec.segment()
    events = merge_segments(segments)
    for e in trace_report.check_events(events):
        errors.append(f"clean merged trace failed the schema lint: {e}")
    bad = [{"ph": "E", "ts": 1.0, "pid": 0, "tid": 3},
           {"ph": "B", "ts": 2.0, "pid": 0, "tid": 4,
            "args": {"corr": "missing-separators"}}]
    bad_errs = trace_report.check_events(bad)
    if not any("dangling E" in e for e in bad_errs):
        errors.append("schema lint no longer detects dangling E events")
    if not any("malformed correlation id" in e for e in bad_errs):
        errors.append("schema lint no longer detects malformed "
                      "correlation ids")
    if not any("unclosed B" in e for e in bad_errs):
        errors.append("schema lint no longer detects unclosed B spans")
    return errors, {"merged_events": len(events),
                    "violation_classes_proven": 3}


def run_ckpt_manifest() -> Tuple[List[str], dict]:
    """Checkpoint-manifest contract self-check (ISSUE 9): a LIVE
    generation written by two CheckpointManager ranks must round-trip
    through the schema validator and the commit barrier — and the
    barrier must still reject each known violation class (mismatched
    shard checksum, stale world_version, missing rank), so a green run
    can't mean a gutted validator."""
    import copy
    import json as _json
    import tempfile

    import numpy as np

    from horovod_tpu.checkpoint import (CheckpointManager,
                                        generation_complete,
                                        validate_manifest)
    errors: List[str] = []
    tree = {"w": np.arange(40, dtype=np.float32),
            "b": np.ones((3,), np.float32)}
    with tempfile.TemporaryDirectory() as d:
        mgrs = [CheckpointManager(d, rank=r, world_size=2, redundancy=1)
                for r in range(2)]
        try:
            for m in mgrs:
                m.snapshot(tree, step=1)
            for m in mgrs:
                if not m.wait_idle(60):
                    errors.append("checkpoint write did not finish")
            manifests = mgrs[0]._disk_manifests(1)
        finally:
            for m in mgrs:
                m.close(flush=False)
    if sorted(manifests) != [0, 1]:
        return errors + [f"round-trip produced manifests for ranks "
                         f"{sorted(manifests)}, expected [0, 1]"], {}
    for r, m in manifests.items():
        # re-parse through JSON: the validator must accept exactly what
        # lands on disk/KV, not the in-memory dict
        for e in validate_manifest(_json.loads(_json.dumps(m))):
            errors.append(f"live manifest rank {r} failed schema: {e}")
    ok, errs = generation_complete(manifests)
    if not ok:
        errors += [f"live generation failed the commit barrier: {e}"
                   for e in errs]
    # violation class 1: corrupted shard checksum
    bad = copy.deepcopy(manifests)
    bad[1]["shard_checksums"]["1"] = "0" * 64
    ok, errs = generation_complete(bad)
    if ok or not any("checksum mismatch" in e for e in errs):
        errors.append("barrier no longer rejects a mismatched shard "
                      "checksum")
    # violation class 2: stale world_version (generation spans a reset)
    bad = copy.deepcopy(manifests)
    bad[1]["world_version"] += 1
    ok, errs = generation_complete(bad)
    if ok or not any("stale world_version" in e for e in errs):
        errors.append("barrier no longer rejects a stale world_version")
    # violation class 3: partial generation (a rank never committed)
    ok, errs = generation_complete({0: manifests[0]})
    if ok or not any("missing manifests" in e for e in errs):
        errors.append("barrier no longer rejects a partial generation")
    return errors, {"manifests": len(manifests),
                    "violation_classes_proven": 3}


CHECKS: Dict[str, Callable[[], Tuple[List[str], dict]]] = {
    "lockcheck": run_lockcheck,
    "divcheck": run_divcheck,
    "knobs": run_knobs,
    "metrics": run_metrics,
    "faults": run_faults,
    "trace_schema": run_trace_schema,
    "ckpt_manifest": run_ckpt_manifest,
    "errflow": run_errflow,
}

# lints whose findings carry file:line and can be filtered to a changed
# subset; also the pure-AST set --changed runs (knobs is pure-AST too
# but registry-global: dead-knob detection needs the whole tree either
# way, and it is cheap)
FILE_SCOPED = ("lockcheck", "divcheck", "errflow")
CHANGED_MODE_LINTS = ("lockcheck", "divcheck", "knobs", "errflow")


def changed_files(base: str = "main") -> set:
    """Repo-relative paths this branch is working on: commits since the
    merge-base with ``base`` (``base...HEAD`` — NOT ``base``'s tip, so
    files that only moved on main never leak into the filter), plus
    staged/working-tree edits vs HEAD, plus untracked files. Paths are
    as the lint reports spell them (relative to the repo root)."""
    import subprocess
    out: set = set()
    for args in (["git", "diff", "--name-only", f"{base}...HEAD"],
                 ["git", "diff", "--name-only", "HEAD"],
                 ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            res = subprocess.run(args, cwd=REPO, capture_output=True,
                                 text=True, timeout=30)
        except Exception:
            continue
        if res.returncode == 0:
            out.update(l.strip() for l in res.stdout.splitlines()
                       if l.strip())
    return out


def run_checks(only: Optional[List[str]] = None,
               changed: Optional[set] = None) -> dict:
    """Run the selected lints; returns the machine-readable report dict
    ``{"ok": bool, "checks": {name: {"ok", "errors", "stats"}}}``.
    ``changed`` (a repo-relative path set) switches the file-scoped
    lints to filtered findings — the ``--changed`` dev loop."""
    if changed is not None and not only:
        names = list(CHANGED_MODE_LINTS)
    else:
        names = list(CHECKS) if not only else only
    unknown = [n for n in names if n not in CHECKS]
    if unknown:
        raise ValueError(f"unknown lint(s): {', '.join(unknown)} "
                         f"(valid: {', '.join(CHECKS)})")
    report: dict = {"ok": True, "checks": {}}
    for name in names:
        try:
            if changed is not None and name in FILE_SCOPED:
                errors, stats = CHECKS[name](changed=changed)
            else:
                errors, stats = CHECKS[name]()
        except Exception as e:  # a crashed lint is a failed lint, loudly
            errors, stats = [f"lint crashed: {type(e).__name__}: {e}"], {}
        report["checks"][name] = {"ok": not errors, "errors": errors,
                                  "stats": stats}
        if errors:
            report["ok"] = False
    return report


def _print_text(report: dict):
    for name, res in report["checks"].items():
        mark = "OK  " if res["ok"] else "FAIL"
        stats = res["stats"]
        summary = ", ".join(
            f"{k}={v}" for k, v in stats.items()
            if not isinstance(v, (list, dict)))
        print(f"[{mark}] {name}" + (f" ({summary})" if summary else ""))
        for e in res["errors"]:
            print(f"       - {e}")
        for s in stats.get("suppressions", []):
            print(f"       suppressed [{s['check']}] {s['file']}:"
                  f"{s['line']} — {s['reason']}")
        for a in stats.get("agreed_sites", []):
            print(f"       agreed[{a['what']}] {a['file']}:{a['line']} "
                  f"— {a['how']}")
        for s in stats.get("seams", []):
            print(f"       seam {s['file']}:{s['line']} {s['func']} "
                  f"— {s['how']}")
    n_fail = sum(1 for r in report["checks"].values() if not r["ok"])
    total = len(report["checks"])
    print(f"{total - n_fail}/{total} lints passed")


_LOC_RE = re.compile(r"^([\w./-]+\.py):(\d+):\s*(.*)$", re.S)


def _gh_escape(msg: str) -> str:
    # workflow-command message encoding: % first, then the line breaks
    return (msg.replace("%", "%25").replace("\r", "%0D")
            .replace("\n", "%0A"))


def _print_github(report: dict):
    """GitHub Actions workflow-command emitter: one ``::error`` per
    finding, annotated onto the file/line when the error string carries
    a ``path:line:`` prefix."""
    for name, res in report["checks"].items():
        for e in res["errors"]:
            m = _LOC_RE.match(e)
            if m:
                msg = _gh_escape(f"[{name}] {m.group(3)}")
                print(f"::error file={m.group(1)},line={m.group(2)}::{msg}")
            else:
                print("::error::" + _gh_escape(f"[{name}] {e}"))
    n_fail = sum(1 for r in report["checks"].values() if not r["ok"])
    total = len(report["checks"])
    print(f"{total - n_fail}/{total} lints passed")


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        description="Unified static-analysis driver "
                    "(docs/static_analysis.md)")
    ap.add_argument("--format", choices=("text", "json", "github"),
                    default="text")
    ap.add_argument("--only", default=None,
                    help="comma-separated subset of lints to run")
    ap.add_argument("--changed", action="store_true",
                    help="fast dev loop: pure-AST lints only, findings "
                         "filtered to files changed vs --base")
    ap.add_argument("--base", default="main",
                    help="git ref --changed diffs against (default: main)")
    ap.add_argument("--list", action="store_true",
                    help="list available lints and exit")
    args = ap.parse_args(argv)
    if args.list:
        for name in CHECKS:
            print(name)
        return 0
    only = [s.strip() for s in args.only.split(",")] if args.only else None
    changed = changed_files(args.base) if args.changed else None
    try:
        report = run_checks(only, changed=changed)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    if args.format == "json":
        print(json.dumps(report, indent=2))
    elif args.format == "github":
        _print_github(report)
    else:
        if changed is not None:
            touched = sorted(f for f in changed if f.endswith(".py"))
            print(f"[--changed] {len(touched)} changed .py file(s) vs "
                  f"{args.base}; full scan remains the tier-1/CI default")
        _print_text(report)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
