"""CLI shim for the failpoint-namespace lint.

The implementation lives in :mod:`horovod_tpu.analysis.faultcheck`
(ISSUE 15 folded the scan into the analysis package; the call-site pass
is AST-based now, so docstring examples no longer need a special case);
``tools/check.py`` runs it next to the other lints. This entry point
remains for single-lint runs: ``python tools/check_fault_names.py``;
exit code 0 means clean.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from horovod_tpu.analysis.faultcheck import (  # noqa: E402,F401
    NAME_RE, scan_call_sites, validate_call_sites, validate_specs)


def main() -> int:
    from horovod_tpu.analysis import faultcheck
    errors, stats = faultcheck.run()
    if errors:
        print(f"{len(errors)} failpoint declaration error(s):")
        for e in errors:
            print(f"  - {e}")
        return 1
    unplaced = stats.get("unplaced") or []
    print(f"{stats['declared']} declared failpoints OK; "
          f"{stats['call_sites']} call site(s) verified"
          + (f"; declared but unplaced: {', '.join(unplaced)}" if unplaced
             else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
