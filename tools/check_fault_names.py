"""Lint the failpoint namespace (the ``check_metric_names.py`` pattern):

1. every entry in ``horovod_tpu.faults.FAULT_SPECS`` must match the fault
   name regex and carry a non-empty help string;
2. every ``failpoint("...")`` call site under ``horovod_tpu/`` must use a
   name declared in ``FAULT_SPECS`` (``test.*`` names are reserved for
   suites and must not appear in framework code).

Thin shim: ``tools/check.py`` is the unified driver that runs this next
to the lockcheck/knob/metric/trace-schema lints (one tier-1 test,
tests/test_check.py). This entry point remains for single-lint runs:
``python tools/check_fault_names.py``; exit code 0 means clean.
"""

from __future__ import annotations

import os
import re
import sys
from typing import Dict, List, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

CALL_RE = re.compile(r"""failpoint\(\s*(['"])([^'"]+)\1\s*\)""")


def validate_specs(specs: Dict[str, str]) -> List[str]:
    """Return a list of error strings; empty means the table is clean."""
    from horovod_tpu.faults import NAME_RE
    errors = []
    for name, help_str in sorted(specs.items()):
        if not NAME_RE.match(name):
            errors.append(f"{name}: does not match {NAME_RE.pattern}")
        if name.startswith("test."):
            errors.append(f"{name}: the test. prefix is reserved for "
                          f"suite-local failpoints")
        if not isinstance(help_str, str) or not help_str.strip():
            errors.append(f"{name}: missing help string")
    return errors


def scan_call_sites(pkg_root: str) -> List[Tuple[str, int, str]]:
    """Every ``failpoint("name")`` literal under ``pkg_root``:
    (relpath, lineno, name)."""
    sites = []
    for dirpath, _dirnames, filenames in os.walk(pkg_root):
        if "__pycache__" in dirpath:
            continue
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            # faults.py itself only *defines* failpoint(); the matches in
            # it are docstring examples, not call sites
            if os.path.relpath(path, pkg_root) == "faults.py":
                continue
            with open(path) as f:
                for lineno, line in enumerate(f, 1):
                    for m in CALL_RE.finditer(line):
                        sites.append((os.path.relpath(path, pkg_root),
                                      lineno, m.group(2)))
    return sites


def validate_call_sites(specs: Dict[str, str],
                        sites: List[Tuple[str, int, str]]) -> List[str]:
    errors = []
    for rel, lineno, name in sites:
        if name not in specs:
            errors.append(
                f"{rel}:{lineno}: failpoint({name!r}) is not declared in "
                f"horovod_tpu.faults.FAULT_SPECS")
    return errors


def main() -> int:
    from horovod_tpu.faults import FAULT_SPECS
    pkg_root = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "horovod_tpu")
    errors = validate_specs(FAULT_SPECS)
    sites = scan_call_sites(pkg_root)
    errors += validate_call_sites(FAULT_SPECS, sites)
    placed = {name for _, _, name in sites}
    unused = sorted(set(FAULT_SPECS) - placed)
    if errors:
        print(f"{len(errors)} failpoint declaration error(s):")
        for e in errors:
            print(f"  - {e}")
        return 1
    print(f"{len(FAULT_SPECS)} declared failpoints OK; "
          f"{len(sites)} call site(s) verified"
          + (f"; declared but unplaced: {', '.join(unused)}" if unused
             else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
