"""CLI shim for the exception-propagation & resource-lifecycle analyzer.

The implementation lives in :mod:`horovod_tpu.analysis.errflow`;
``tools/check.py`` runs it next to the other lints. This entry point
exists for single-lint runs and for checking paths outside the package
(the test fixtures do this)::

    python tools/errflow.py                      # horovod_tpu/
    python tools/errflow.py path/to/module.py --format=json
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from horovod_tpu.analysis.errflow import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
