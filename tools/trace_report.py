"""Offline straggler / critical-path analyzer for merged cluster traces.

Input: a Chrome-trace JSON file as served by ``GET /trace`` on the
rendezvous/KV server (object form with ``traceEvents``), a bare event
array (e.g. a per-rank timeline or flight-recorder dump), or a
crash-truncated file — loading goes through the tolerant
``horovod_tpu.trace.load_trace_events``.

Report (``python tools/trace_report.py TRACE.json``):

- **per-collective arrival skew** — for every correlation id seen on >= 2
  ranks, the gap between the first-arrival and last-arrival rank,
  aggregated per op kind (count / mean / p50 / max);
- **top-straggler ranking** — ranks ordered by how often they arrived
  last, with their mean lateness;
- **per-step wire-vs-gap breakdown** — per rank, mean STEP span time
  split into dispatch (wire) time vs everything else (gap);
- **critical-path estimate** — dispatch time plus the arrival skew the
  whole world waited out, attributed to the rank that caused each wait.

Schema self-check (``--check``, the ``check_metric_names.py`` /
``check_fault_names.py`` lint pattern, run from a tier-1 test): validates
event structure, B/E balance per (pid, tid), correlation-id format, and
the once-per-phase-per-rank invariant. Exit code 0 means clean.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

VALID_PHASES = ("B", "E", "X", "i", "C", "M", "b", "e")


# ---------------------------------------------------------------------------
# analysis
# ---------------------------------------------------------------------------

def _corr_of(ev: dict) -> Optional[str]:
    args = ev.get("args")
    if isinstance(args, dict):
        c = args.get("corr")
        if isinstance(c, str):
            return c
    return None


def arrival_skew(events: List[dict]) -> Dict[str, dict]:
    """Per-correlation-id arrival skew from the merged "B" (enqueue)
    events: ``corr -> {kind, arrivals: {pid: ts_us}, first, last,
    skew_us}``. Only ids seen on >= 2 pids count."""
    arrivals: Dict[str, dict] = {}
    for ev in events:
        if ev.get("ph") != "B":
            continue
        corr = _corr_of(ev)
        if corr is None:
            continue
        ent = arrivals.setdefault(corr, {"kind": ev.get("name", ""),
                                         "arrivals": {}})
        ent["arrivals"].setdefault(int(ev.get("pid", 0)), float(ev["ts"]))
    out: Dict[str, dict] = {}
    for corr, ent in arrivals.items():
        ranks = ent["arrivals"]
        if len(ranks) < 2:
            continue
        first = min(ranks, key=ranks.get)
        last = max(ranks, key=ranks.get)
        out[corr] = {"kind": ent["kind"], "arrivals": ranks,
                     "first": first, "last": last,
                     "skew_us": ranks[last] - ranks[first]}
    return out


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(int(q * (len(sorted_vals) - 1)), len(sorted_vals) - 1)
    return sorted_vals[i]


def skew_by_kind(skews: Dict[str, dict]) -> Dict[str, dict]:
    by_kind: Dict[str, List[float]] = {}
    for ent in skews.values():
        by_kind.setdefault(ent["kind"], []).append(ent["skew_us"])
    out = {}
    for kind, vals in by_kind.items():
        vals.sort()
        out[kind] = {"count": len(vals),
                     "mean_us": sum(vals) / len(vals),
                     "p50_us": _percentile(vals, 0.5),
                     "max_us": vals[-1]}
    return out


def wire_by_link(events: List[dict]) -> Dict[str, dict]:
    """Per-kind cluster wire bytes by fabric link (ISSUE 10), summed from
    the ``link_bytes`` split the engine stamps on enqueue (B) events:
    ``kind -> {"ici"/"dcn"/"flat": bytes}``. Hierarchical legs surface as
    separate ici/dcn rows — the observable face of the 1/local_size
    cross-slice traffic reduction; traces from older runs (no stamps)
    yield an empty table."""
    out: Dict[str, dict] = {}
    for ev in events:
        if ev.get("ph") != "B":
            continue
        args = ev.get("args")
        lb = args.get("link_bytes") if isinstance(args, dict) else None
        if not isinstance(lb, dict):
            continue
        ent = out.setdefault(str(ev.get("name", "")), {})
        for link, b in lb.items():
            try:
                ent[str(link)] = ent.get(str(link), 0) + int(b)
            except (TypeError, ValueError):
                continue
    return out


def straggler_ranking(skews: Dict[str, dict]) -> List[dict]:
    """Ranks ordered by how often they arrived last (ties by total
    lateness): ``[{rank, last_count, total_late_us, mean_late_us}]``."""
    per_rank: Dict[int, dict] = {}
    for ent in skews.values():
        r = ent["last"]
        acc = per_rank.setdefault(r, {"rank": r, "last_count": 0,
                                      "total_late_us": 0.0})
        acc["last_count"] += 1
        acc["total_late_us"] += ent["skew_us"]
    out = sorted(per_rank.values(),
                 key=lambda a: (-a["last_count"], -a["total_late_us"]))
    for acc in out:
        acc["mean_late_us"] = acc["total_late_us"] / acc["last_count"]
    return out


def wire_vs_gap(events: List[dict]) -> Dict[int, dict]:
    """Per rank: mean per-step breakdown of STEP span time into dispatch
    ("wire", the X dispatch spans inside the step window) vs everything
    else ("gap": host time, stragglers, input pipeline). Ranks without
    STEP spans report totals over the whole trace instead."""
    steps: Dict[int, List[Tuple[float, float]]] = {}
    dispatch: Dict[int, List[Tuple[float, float]]] = {}
    span: Dict[int, Tuple[float, float]] = {}
    for ev in events:
        pid = int(ev.get("pid", 0))
        if ev.get("ph") != "X":
            if ev.get("ph") in ("B", "E"):
                t = float(ev.get("ts", 0.0))
                lo, hi = span.get(pid, (t, t))
                span[pid] = (min(lo, t), max(hi, t))
            continue
        t0 = float(ev.get("ts", 0.0))
        dur = float(ev.get("dur", 0.0))
        lo, hi = span.get(pid, (t0, t0 + dur))
        span[pid] = (min(lo, t0), max(hi, t0 + dur))
        if ev.get("name") == "STEP":
            steps.setdefault(pid, []).append((t0, t0 + dur))
        elif ev.get("cat") == "dispatch" or \
                str(ev.get("name", "")).startswith("XLA_"):
            dispatch.setdefault(pid, []).append((t0, t0 + dur))
    out: Dict[int, dict] = {}
    for pid in sorted(set(steps) | set(dispatch) | set(span)):
        d = dispatch.get(pid, [])
        st = steps.get(pid, [])
        if st:
            total = sum(b - a for a, b in st)
            wire = sum(min(b, sb) - max(a, sa)
                       for a, b in d for sa, sb in st
                       if min(b, sb) > max(a, sa))
            n = len(st)
        else:
            lo, hi = span.get(pid, (0.0, 0.0))
            total = hi - lo
            wire = sum(b - a for a, b in d)
            n = 1 if total > 0 else 0
        out[pid] = {"steps": len(st), "total_us": total,
                    "wire_us": min(wire, total),
                    "gap_us": max(total - wire, 0.0),
                    "per_step_total_us": total / n if n else 0.0}
    return out


def gap_attribution(events: List[dict],
                    skews: Optional[Dict[str, dict]] = None
                    ) -> Dict[int, dict]:
    """Per-rank attribution of step time into its four sinks (ISSUE 14 /
    ROADMAP item 5: the post-tune report must prove where the remaining
    MFU gap lives):

    - **dispatch** — host time spent inside XLA launches (the X spans of
      ``cat == "dispatch"`` clipped to STEP windows): per-launch
      overhead, the thing replay/overlap/fusion shrink;
    - **straggler_wait** — time this rank sat waiting for LATER arrivals
      at correlated collectives (per corr id: last-arrival ts minus this
      rank's arrival ts, clipped into the step windows' total): load
      imbalance, input-pipeline skew;
    - **wire** — collective in-flight time (B→E spans clipped to STEP
      windows) beyond what dispatch and straggler-wait already explain:
      actual byte movement on the critical path, the thing
      compression/topology-selection shrink;
    - **compute** — everything else: the model's math plus any host gap.
      After the tuner has flattened the other three, this is the MFU
      numerator's home.

    Ranks without STEP spans attribute over their whole trace span (the
    ``wire_vs_gap`` convention). All figures are totals across the
    rank's steps, with a ``pct`` breakdown of the step total."""
    if skews is None:
        skews = arrival_skew(events)
    steps: Dict[int, List[Tuple[float, float]]] = {}
    dispatch: Dict[int, List[Tuple[float, float]]] = {}
    opens: Dict[Tuple[int, str], float] = {}
    inflight: Dict[int, List[Tuple[float, float]]] = {}
    span: Dict[int, Tuple[float, float]] = {}

    def _grow(pid, lo, hi):
        a, b = span.get(pid, (lo, hi))
        span[pid] = (min(a, lo), max(b, hi))

    for ev in events:
        ph = ev.get("ph")
        pid = int(ev.get("pid", 0))
        if ph == "X":
            t0 = float(ev.get("ts", 0.0))
            dur = float(ev.get("dur", 0.0))
            _grow(pid, t0, t0 + dur)
            if ev.get("name") == "STEP":
                steps.setdefault(pid, []).append((t0, t0 + dur))
            elif ev.get("cat") == "dispatch" or \
                    str(ev.get("name", "")).startswith("XLA_"):
                dispatch.setdefault(pid, []).append((t0, t0 + dur))
        elif ph in ("B", "E"):
            t = float(ev.get("ts", 0.0))
            _grow(pid, t, t)
            corr = _corr_of(ev)
            if corr is None:
                continue
            if ph == "B":
                opens[(pid, corr)] = t
            else:
                t0 = opens.pop((pid, corr), None)
                if t0 is not None and t > t0:
                    inflight.setdefault(pid, []).append((t0, t))
    # per-rank straggler wait: how long each correlated collective's
    # last arrival made THIS rank wait past its own arrival
    waited: Dict[int, float] = {}
    for ent in skews.values():
        last_ts = ent["arrivals"][ent["last"]]
        for pid, ts in ent["arrivals"].items():
            if last_ts > ts:
                waited[pid] = waited.get(pid, 0.0) + (last_ts - ts)

    def _clip_total(spans, windows):
        if not windows:
            return sum(b - a for a, b in spans)
        return sum(min(b, wb) - max(a, wa)
                   for a, b in spans for wa, wb in windows
                   if min(b, wb) > max(a, wa))

    out: Dict[int, dict] = {}
    for pid in sorted(set(steps) | set(dispatch) | set(inflight)
                      | set(span)):
        st = steps.get(pid, [])
        if st:
            total = sum(b - a for a, b in st)
            n = len(st)
        else:
            lo, hi = span.get(pid, (0.0, 0.0))
            total, n = hi - lo, 1 if span.get(pid) else 0
        disp = min(_clip_total(dispatch.get(pid, []), st), total)
        wait = min(waited.get(pid, 0.0), max(total - disp, 0.0))
        infl = _clip_total(inflight.get(pid, []), st)
        wire = min(max(infl - disp - wait, 0.0),
                   max(total - disp - wait, 0.0))
        compute = max(total - disp - wait - wire, 0.0)
        row = {"steps": len(st), "total_us": total,
               "compute_us": compute, "dispatch_us": disp,
               "wire_us": wire, "straggler_wait_us": wait}
        row["pct"] = {
            k[:-3]: (round(100.0 * row[k] / total, 2) if total > 0
                     else 0.0)
            for k in ("compute_us", "dispatch_us", "wire_us",
                      "straggler_wait_us")}
        row["per_step_total_us"] = total / n if n else 0.0
        out[pid] = row
    return out


def critical_path(events: List[dict],
                  skews: Dict[str, dict]) -> dict:
    """A coarse critical-path estimate: total dispatch (wire) time plus
    the arrival skew the world waited out per collective, attributed to
    the last-arrival rank of each. ``{total_us, wire_us, wait_us,
    wait_by_rank: {rank: us}}``."""
    wire = sum(float(ev.get("dur", 0.0)) for ev in events
               if ev.get("ph") == "X" and ev.get("cat") == "dispatch")
    wait_by_rank: Dict[int, float] = {}
    for ent in skews.values():
        wait_by_rank[ent["last"]] = \
            wait_by_rank.get(ent["last"], 0.0) + ent["skew_us"]
    wait = sum(wait_by_rank.values())
    return {"total_us": wire + wait, "wire_us": wire, "wait_us": wait,
            "wait_by_rank": wait_by_rank}


def overlap_report(events: List[dict]) -> dict:
    """Comm/compute-overlap summary (ISSUE 6): how much wire time sits on
    the step critical path, and how much of the collectives' in-flight
    time was hidden off it.

    - ``wire_on_critical_path_pct`` — dispatch (wire-blocking) span time
      as a fraction of total step time: the share of the step the host/
      device spent *inside* collective launches instead of math. Lower
      with overlap on = wire left the critical path.
    - ``overlap_efficiency_pct`` — 1 − wire_on_cp / collective in-flight
      time (B→E spans): a collective that is in flight for 10 ms but only
      blocks the step for 1 ms was 90% hidden. None when the trace has no
      closed collective spans.

    Driven by ``bench.py`` over the PR 5 trace ring (overlap on vs off,
    same world, same model) so overlap wins land in the BENCH_r*
    trajectory and regressions are visible."""
    wg = wire_vs_gap(events)
    total_us = sum(r["total_us"] for r in wg.values())
    wire_us = sum(r["wire_us"] for r in wg.values())
    opens: Dict[Tuple[int, str], float] = {}
    inflight_us = 0.0
    spans = 0
    for ev in events:
        corr = _corr_of(ev)
        if corr is None:
            continue
        pid = int(ev.get("pid", 0))
        if ev.get("ph") == "B":
            opens[(pid, corr)] = float(ev.get("ts", 0.0))
        elif ev.get("ph") == "E":
            t0 = opens.pop((pid, corr), None)
            if t0 is not None:
                inflight_us += max(float(ev.get("ts", 0.0)) - t0, 0.0)
                spans += 1
    return {
        "total_us": total_us,
        "wire_us": wire_us,
        "inflight_us": inflight_us,
        "collective_spans": spans,
        "wire_on_critical_path_pct": (
            round(100.0 * wire_us / total_us, 2) if total_us > 0 else None),
        "overlap_efficiency_pct": (
            round(100.0 * max(0.0, 1.0 - wire_us / inflight_us), 2)
            if inflight_us > 0 else None),
    }


def analyze(events: List[dict]) -> dict:
    """The full report as a plain dict (what ``main`` prints; tests and
    notebooks call this directly)."""
    skews = arrival_skew(events)
    ranking = straggler_ranking(skews)
    by_kind = skew_by_kind(skews)
    links = wire_by_link(events)
    for kind, ent in by_kind.items():
        if kind in links:
            ent["wire_bytes_by_link"] = links[kind]
    return {
        "events": len(events),
        "ranks": sorted({int(e.get("pid", 0)) for e in events
                         if e.get("ph") in ("B", "E", "X")}),
        "correlated_collectives": len(skews),
        "skew_by_kind": by_kind,
        "wire_by_link": links,
        "stragglers": ranking,
        "top_straggler": ranking[0]["rank"] if ranking else None,
        "wire_vs_gap": wire_vs_gap(events),
        "gap_attribution": gap_attribution(events, skews),
        "critical_path": critical_path(events, skews),
        "overlap": overlap_report(events),
    }


# ---------------------------------------------------------------------------
# --check: trace schema + correlation-invariant lint
# ---------------------------------------------------------------------------

def check_events(events: List[dict]) -> List[str]:
    """Validate the merged-trace schema; returns error strings (empty =
    clean):

    - every event is an object with a known ``ph``, a numeric ``ts``
      (metadata excepted) and an integer ``pid``;
    - "B"/"E" balance per (pid, tid), with no dangling end;
    - every correlation id parses as ``name#world_version#seq``;
    - per (pid, corr): at most one enqueue (B) and one complete (E) —
      the exactly-once-per-phase invariant the merger guarantees."""
    from horovod_tpu.trace import parse_corr
    errors: List[str] = []
    depth: Dict[Tuple[int, int], int] = {}
    seen: Dict[Tuple[int, str], Dict[str, int]] = {}
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            errors.append(f"event {i}: not an object")
            continue
        ph = ev.get("ph")
        if ph not in VALID_PHASES:
            errors.append(f"event {i}: unknown phase {ph!r}")
            continue
        if ph != "M" and not isinstance(ev.get("ts"), (int, float)):
            errors.append(f"event {i}: missing numeric ts")
        if not isinstance(ev.get("pid"), int):
            errors.append(f"event {i}: missing integer pid")
            continue
        key = (ev.get("pid"), ev.get("tid", 0))
        if ph == "B":
            depth[key] = depth.get(key, 0) + 1
        elif ph == "E":
            if depth.get(key, 0) <= 0:
                errors.append(f"event {i}: dangling E on pid/tid {key}")
            else:
                depth[key] -= 1
        corr = _corr_of(ev)
        if corr is not None:
            try:
                parse_corr(corr)
            except (ValueError, TypeError):
                errors.append(f"event {i}: malformed correlation id "
                              f"{corr!r}")
                continue
            if ph in ("B", "E"):
                phases = seen.setdefault((ev["pid"], corr), {})
                phases[ph] = phases.get(ph, 0) + 1
                if phases[ph] > 1:
                    errors.append(
                        f"event {i}: correlation id {corr!r} appears "
                        f"{phases[ph]}x in phase {ph} on pid {ev['pid']}")
    for key, d in depth.items():
        if d != 0:
            errors.append(f"pid/tid {key}: {d} unclosed B span(s)")
    return errors


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _fmt_us(us: float) -> str:
    return f"{us / 1e3:.2f} ms" if us >= 1e3 else f"{us:.0f} us"


def _b36(n: int) -> str:
    digits = "0123456789abcdefghijklmnopqrstuvwxyz"
    return digits[n % 36]


def schedule_timeline(schedule: str, n_stages: int, n_micro: int,
                      n_virtual: int = 1) -> str:
    """ASCII render of a pipeline schedule's static tick table (ISSUE 16):
    one F/B(/W under zb) row per stage, one column per tick, base-36
    microbatch index in active slots, '.' when the slot idles. The render
    is the ground truth the executor scans — generated from the same
    ``build_schedule_tables`` rows — so what prints here is literally what
    dispatches."""
    from horovod_tpu.parallel.pipeline import (build_schedule_tables,
                                               pipeline_bubble_fraction,
                                               resolve_pipeline_schedule)
    sched, v = resolve_pipeline_schedule(schedule, n_stages, n_micro,
                                         n_virtual)
    tb = build_schedule_tables(sched, n_stages, n_micro, v)
    lines = [f"schedule {sched}  p={n_stages} m={n_micro} v={v}  "
             f"ticks={tb.ticks}  predicted bubble "
             f"{pipeline_bubble_fraction(n_stages, n_micro, sched, v) * 100:.1f}%"]
    slot_rows = [("F", "f_active", "f_m"), ("B", "b_active", "b_m")]
    if tb.split_bw:
        slot_rows.append(("W", "w_active", "w_m"))
    for s in range(n_stages):
        for i, (label, act, mrow) in enumerate(slot_rows):
            head = f"stage {s}  " if i == 0 else " " * 9
            cells = "".join(
                _b36(int(tb.rows[mrow][t, s]))
                if tb.rows[act][t, s] else "."
                for t in range(tb.ticks))
            lines.append(f"{head}{label} {cells}")
    return "\n".join(lines)


def anomaly_report(events: List[dict],
                   meta: Optional[dict] = None) -> dict:
    """Cross-reference an anomaly's flight dump with ``gap_attribution``
    (ISSUE 20): for each rank in the dump, name the culprit phase — the
    dominant sink (compute / dispatch / wire / straggler_wait) of the
    step time the trace ring captured around the anomaly — plus the
    arrival-skew straggler ranking over the same window."""
    skews = arrival_skew(events)
    attr = gap_attribution(events, skews)
    culprits = {}
    for pid, g in attr.items():
        pct = g.get("pct", {})
        if not pct:
            continue
        phase = max(pct, key=lambda k: pct[k])
        culprits[pid] = {"phase": phase, "pct": pct[phase],
                         "per_step_total_us": g.get("per_step_total_us")}
    return {
        "meta": meta or {},
        "events": len(events),
        "culprit_phase": culprits,
        "stragglers": straggler_ranking(skews)[:5],
        "gap_attribution": attr,
    }


def _load_dump_meta(path: str) -> dict:
    """The flight dump's ``otherData`` block (rank, dropped-event count,
    flight_recorder marker) — tolerant of array-form/truncated files."""
    import json as _json
    try:
        with open(path) as f:
            obj = _json.load(f)
        if isinstance(obj, dict):
            return obj.get("otherData", {}) or {}
    except Exception:
        pass
    return {}


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    p = argparse.ArgumentParser(
        description="Straggler / critical-path report over a merged "
                    "cluster trace (GET /trace output), or a static "
                    "pipeline-schedule timeline (--schedule-timeline)")
    p.add_argument("trace", nargs="?", default=None,
                   help="trace JSON file (object or array form; "
                        "truncated files are recovered)")
    p.add_argument("--check", action="store_true",
                   help="validate the event schema and correlation-id "
                        "invariants instead of reporting")
    p.add_argument("--top", type=int, default=5,
                   help="stragglers to list (default 5)")
    p.add_argument("--json", action="store_true",
                   help="emit the report as JSON")
    p.add_argument("--schedule-timeline", metavar="SCHED",
                   help="render the static tick table for a pipeline "
                        "schedule (1f1b|interleaved|zb|auto) instead of "
                        "reading a trace")
    p.add_argument("--stages", type=int, default=4,
                   help="pipeline stages for --schedule-timeline")
    p.add_argument("--micro", type=int, default=8,
                   help="microbatches for --schedule-timeline")
    p.add_argument("--virtual", type=int, default=1,
                   help="virtual chunks per stage for --schedule-timeline")
    p.add_argument("--anomaly", metavar="DUMP",
                   help="cross-reference an anomaly's flight dump "
                        "(hvd_tpu_flight_rank<r>.json) with "
                        "gap_attribution: name the culprit phase of the "
                        "step window the trace ring captured")
    args = p.parse_args(argv)

    if args.schedule_timeline:
        print(schedule_timeline(args.schedule_timeline, args.stages,
                                args.micro, args.virtual))
        return 0
    if args.anomaly:
        from horovod_tpu.trace import load_trace_file
        events = load_trace_file(args.anomaly)
        rep = anomaly_report(events, _load_dump_meta(args.anomaly))
        if args.json:
            print(json.dumps(rep, indent=2, sort_keys=True))
            return 0
        meta = rep["meta"]
        print(f"anomaly flight dump: {args.anomaly}")
        print(f"  rank={meta.get('rank', '?')}  "
              f"events={rep['events']}  "
              f"dropped={meta.get('dropped', 0)}  "
              f"flight_recorder={meta.get('flight_recorder', False)}")
        if rep["culprit_phase"]:
            print("\nculprit phase per rank (dominant step-time sink in "
                  "the captured window):")
            for pid, c in sorted(rep["culprit_phase"].items()):
                print(f"  rank {pid:<4} {c['phase']:<16} "
                      f"{c['pct']:5.1f}% of step "
                      f"(per-step {_fmt_us(c['per_step_total_us'])})")
        else:
            print("\nno step windows in the dump — nothing to attribute")
        if rep["stragglers"]:
            print("\nstragglers in the captured window:")
            for acc in rep["stragglers"][:args.top]:
                print(f"  rank {acc['rank']:<4} last-arrival "
                      f"{acc['last_count']:>4}x   mean lateness "
                      f"{_fmt_us(acc['mean_late_us'])}")
        return 0
    if args.trace is None:
        p.error("a trace file is required unless --schedule-timeline "
                "or --anomaly is given")

    from horovod_tpu.trace import load_trace_file
    events = load_trace_file(args.trace)
    if args.check:
        errors = check_events(events)
        if errors:
            print(f"{len(errors)} trace schema error(s):")
            for e in errors[:50]:
                print(f"  - {e}")
            return 1
        print(f"{len(events)} events OK (schema, B/E balance, "
              f"correlation ids once per phase per rank)")
        return 0

    rep = analyze(events)
    if args.json:
        print(json.dumps(rep, indent=2, sort_keys=True))
        return 0
    print(f"events: {rep['events']}   ranks: {rep['ranks']}   "
          f"correlated collectives: {rep['correlated_collectives']}")
    if rep["skew_by_kind"]:
        print("\narrival skew by kind (first-arrival vs last-arrival rank):")
        for kind, s in sorted(rep["skew_by_kind"].items()):
            links = s.get("wire_bytes_by_link")
            tail = ("  wire[" + " ".join(
                f"{k}={v}" for k, v in sorted(links.items())) + "]"
                if links else "")
            print(f"  {kind:<22} n={s['count']:<5} "
                  f"mean={_fmt_us(s['mean_us']):<10} "
                  f"p50={_fmt_us(s['p50_us']):<10} "
                  f"max={_fmt_us(s['max_us'])}{tail}")
    if rep["wire_by_link"]:
        print("\nwire bytes by fabric link (cluster total, per kind):")
        for kind, links in sorted(rep["wire_by_link"].items()):
            row = "  ".join(f"{k}={v}" for k, v in sorted(links.items()))
            print(f"  {kind:<22} {row}")
    if rep["stragglers"]:
        print(f"\ntop stragglers (of {rep['correlated_collectives']} "
              f"correlated collectives):")
        for acc in rep["stragglers"][:args.top]:
            print(f"  rank {acc['rank']:<4} last-arrival "
                  f"{acc['last_count']:>4}x   mean lateness "
                  f"{_fmt_us(acc['mean_late_us'])}")
    if rep["wire_vs_gap"]:
        print("\nwire vs gap per rank:")
        for pid, w in sorted(rep["wire_vs_gap"].items()):
            print(f"  rank {pid:<4} steps={w['steps']:<4} "
                  f"wire={_fmt_us(w['wire_us']):<10} "
                  f"gap={_fmt_us(w['gap_us']):<10} "
                  f"(per-step {_fmt_us(w['per_step_total_us'])})")
    if rep["gap_attribution"]:
        print("\ngap attribution (per-step time -> compute / dispatch / "
              "wire / straggler-wait):")
        for pid, g in sorted(rep["gap_attribution"].items()):
            pct = g["pct"]
            print(f"  rank {pid:<4} steps={g['steps']:<4} "
                  f"compute={pct['compute']:5.1f}%  "
                  f"dispatch={pct['dispatch']:5.1f}%  "
                  f"wire={pct['wire']:5.1f}%  "
                  f"straggler={pct['straggler_wait']:5.1f}%  "
                  f"(per-step {_fmt_us(g['per_step_total_us'])})")
    cp = rep["critical_path"]
    print(f"\ncritical-path estimate: {_fmt_us(cp['total_us'])} "
          f"(wire {_fmt_us(cp['wire_us'])} + straggler waits "
          f"{_fmt_us(cp['wait_us'])})")
    for r, us in sorted(cp["wait_by_rank"].items(),
                        key=lambda kv: -kv[1]):
        print(f"  waits attributed to rank {r}: {_fmt_us(us)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
