"""Generate docs/api.md from the package's public docstrings.

Run from the repo root: ``python tools/gen_api_docs.py``. Kept as a script
(not a build step) so the committed docs/api.md is reviewable; CI checks it
is in sync via tests/test_examples.py.
"""

from __future__ import annotations

import importlib
import inspect
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

SECTIONS = [
    ("Lifecycle & topology", "horovod_tpu", [
        "init", "shutdown", "is_initialized", "rank", "size", "local_rank",
        "local_size", "cross_rank", "cross_size", "is_homogeneous", "mesh"]),
    ("Collectives (sync)", "horovod_tpu", [
        "allreduce", "grouped_allreduce", "allgather", "broadcast",
        "alltoall", "reducescatter", "barrier", "join"]),
    ("Collectives (async handles)", "horovod_tpu", [
        "allreduce_async", "grouped_allreduce_async", "allgather_async",
        "broadcast_async", "alltoall_async", "reducescatter_async", "poll",
        "synchronize"]),
    ("Step-capture replay", "horovod_tpu", [
        "step_begin", "step_end", "step"]),
    ("", "horovod_tpu.core.replay", []),
    ("Fault injection & robustness", "horovod_tpu.faults", [
        "failpoint", "arm", "disarm", "break_hangs", "hits", "arm_from_kv",
        "enabled", "FaultRegistry", "DROP"]),
    ("", "horovod_tpu.common.retry", ["retrying", "backoff_delays"]),
    ("Metrics & telemetry", "horovod_tpu", ["metrics_snapshot"]),
    ("", "horovod_tpu.metrics", [
        "registry", "Registry", "Counter", "Gauge", "Histogram", "EventLog",
        "MetricsEmitter", "render_prometheus", "render_prometheus_cluster",
        "publish_snapshot"]),
    ("Step health & anomaly detection", "horovod_tpu.observability", [
        "StepDigest", "RollingBaseline", "AnomalyDetector", "Anomaly",
        "StepHealthMonitor", "FlightDumper", "HBMSampler",
        "ANOMALY_CLASSES"]),
    ("State synchronization", "horovod_tpu", [
        "broadcast_parameters", "broadcast_optimizer_state",
        "broadcast_object", "allgather_object", "allreduce_sparse"]),
    ("Optimizers & compression", "horovod_tpu", [
        "DistributedOptimizer", "DistributedDeltaAdasumOptimizer",
        "Compression"]),
    ("Gradient wire codecs", "horovod_tpu.ops.compression", [
        "resolve_codec", "wire_itemsize", "encode", "decode", "decode_sum",
        "ef_encode", "FP8Compressor", "Int8Compressor"]),
    ("", "horovod_tpu.ops.collectives", [
        "build_codec_allreduce", "codec_residual_elems", "ef_allreduce_p",
        "replay_residual_layout"]),
    ("Functional optimizer API", "horovod_tpu.optimizer", [
        "distributed", "DistributedState", "DistributedEagerOptimizer",
        "ShardedEagerState", "zero1_state_specs",
        "distributed_delta_adasum"]),
    ("Sharded (ZeRO-1) collective builders", "horovod_tpu.ops.collectives", [
        "build_grouped_reducescatter", "build_grouped_allgather",
        "build_sharded_step", "build_sharded_update", "build_replay_step",
        "shard_spec"]),
    ("Topology & algorithm selection", "horovod_tpu.parallel.mesh", [
        "Topology", "detect_topology", "world_mesh", "hierarchical_mesh",
        "training_mesh", "multislice_mesh"]),
    ("", "horovod_tpu.ops.collectives", [
        "choose_algorithm", "validate_algorithm", "link_split",
        "tree_groups", "build_tree_allreduce",
        "build_hierarchical_allreduce", "build_hierarchical_allgather"]),
    ("Comm/compute overlap", "horovod_tpu.common.env", ["apply_xla_lhs"]),
    ("Reduce ops & exceptions", "horovod_tpu", [
        "ReduceOp", "HorovodInternalError", "HostsUpdatedInterrupt",
        "DuplicateNameError"]),
    ("Elastic training", "horovod_tpu.elastic", [
        "run", "State", "ObjectState", "TPUState"]),
    ("Checkpointing", "horovod_tpu.checkpoint", [
        "CheckpointManager", "RestoreResult", "CheckpointRestoreError",
        "build_manifest", "validate_manifest", "generation_complete",
        "checksum", "reshard_ranges", "zero1_reshard"]),
    ("Cluster run API", "horovod_tpu.runner", [
        "run", "run_elastic"]),
    ("Replicated control plane", "horovod_tpu.runner.replication", [
        "ReplicaCoordinator", "ReplicationConfig"]),
    ("", "horovod_tpu.runner.http_client", [
        "Endpoints", "resolve_endpoints", "parse_endpoint_spec",
        "KVBackpressure"]),
    ("Hierarchical telemetry", "horovod_tpu.runner.aggregator", [
        "SliceAggregator", "TelemetryRoute"]),
    ("Estimator & store", "horovod_tpu", []),
    ("Models", "horovod_tpu.models.transformer", [
        "TransformerConfig", "init_params", "forward_block", "lean_lm_loss",
        "make_train_step", "make_spmd_loss", "shard_params"]),
    ("", "horovod_tpu.models.vit", ["ViT", "ViT_B16", "ViT_S16"]),
    ("", "horovod_tpu.models.resnet", ["ResNet50", "ResNet101", "ResNet152"]),
    ("Parallelism kernels", "horovod_tpu.parallel.ring_attention", [
        "ring_attention_p", "local_attention"]),
    ("", "horovod_tpu.parallel.ulysses", ["ulysses_attention_p"]),
    ("", "horovod_tpu.parallel.flash_attention", ["flash_attention_local"]),
    ("", "horovod_tpu.parallel.moe", ["moe_layer_p", "MoEParams"]),
    ("", "horovod_tpu.parallel.pipeline", []),
    ("Ops", "horovod_tpu.ops.sync_batch_norm", []),
    ("", "horovod_tpu.ops.fused_batch_norm", ["FusedBatchNorm",
                                              "batch_norm_train"]),
    ("", "horovod_tpu.ops.adasum", ["adasum_combine"]),
    ("Callbacks", "horovod_tpu.callbacks", []),
    ("Observability", "horovod_tpu.timeline", []),
    ("", "horovod_tpu.stall_inspector", []),
    ("Cross-rank tracing", "horovod_tpu.trace", [
        "TraceRecorder", "TracePublisher", "publish_segment",
        "merge_segments", "collective_skew", "modal_straggler",
        "observe_skew",
        "render_cluster_trace", "clock_offset", "load_trace_events",
        "load_trace_file", "make_corr", "parse_corr"]),
    ("Autotuning", "horovod_tpu.autotune.parameter_manager", []),
    ("", "horovod_tpu.autotune.calibration", [
        "fit_alpha_beta", "derived_tree_threshold_bytes",
        "derived_hier_threshold_bytes", "probe_link_times",
        "agree_times", "fit_measured_topology", "derived_thresholds",
        "calibrate_engine"]),
    ("", "horovod_tpu.autotune.persistence", []),
    ("Static analysis", "horovod_tpu.analysis", []),
    ("", "horovod_tpu.analysis.lockcheck", []),
    ("", "horovod_tpu.analysis.divcheck", []),
    ("", "horovod_tpu.analysis.knobcheck", []),
    ("", "horovod_tpu.analysis.errflow", []),
    ("", "horovod_tpu.analysis.faultcheck", []),
    ("", "horovod_tpu.analysis.metriccheck", []),
    ("", "horovod_tpu.common.knobs", []),
]


def _first_para(doc: str) -> str:
    import re
    doc = inspect.cleandoc(doc or "")
    para = doc.split("\n\n")[0].replace("\n", " ").strip()
    # dataclass-generated docstrings embed default-object reprs with
    # per-process memory addresses — strip them for reproducibility
    return re.sub(r" at 0x[0-9a-f]+", "", para)


def _sig(obj) -> str:
    import re
    try:
        sig = str(inspect.signature(obj))
    except (TypeError, ValueError):
        return ""
    # default-value reprs can embed per-process memory addresses (e.g. flax
    # sentinel objects) — strip them so the output is reproducible
    return re.sub(r" at 0x[0-9a-f]+", "", sig)


def _knob_rows(specs, internal):
    rows = []
    for name in sorted(specs):
        spec = specs[name]
        if bool(spec.get("internal")) is not internal:
            continue
        typ = spec["type"]
        if typ == "choice" and spec.get("choices"):
            typ = "choice: " + "/".join(spec["choices"])
        default = str(spec.get("default", "")) or "(unset)"
        help_str = " ".join(spec["help"].split())
        rows.append(f"| `{name}` | {typ} | `{default}` | {help_str} |")
    return rows


def knob_section():
    """The generated "Configuration knobs" section: rendered from
    horovod_tpu.common.knobs.KNOB_SPECS (the registry the knob lint in
    tools/check.py keeps in sync with the code's actual env reads)."""
    from horovod_tpu.common.knobs import KNOB_SPECS
    out = ["## Configuration knobs",
           "",
           "Generated from `horovod_tpu.common.knobs.KNOB_SPECS` — the "
           "central registry of every environment variable the framework "
           "reads. `python tools/check.py --only knobs` fails on knobs "
           "read but not declared here, and on declared knobs nothing "
           "reads (see docs/static_analysis.md).",
           "",
           "| knob | type | default | description |",
           "| --- | --- | --- | --- |"]
    out += _knob_rows(KNOB_SPECS, internal=False)
    out += ["",
            "Launcher/rendezvous plumbing (set by `tpurun` and the "
            "elastic driver; users rarely set these directly):",
            "",
            "| variable | type | default | description |",
            "| --- | --- | --- | --- |"]
    out += _knob_rows(KNOB_SPECS, internal=True)
    out.append("")
    return out


def main():
    out = ["# API reference",
           "",
           "Generated by `python tools/gen_api_docs.py` from the public "
           "docstrings. The import surface is `import horovod_tpu as hvd` "
           "(drop-in for the reference's `import horovod.torch as hvd` "
           "call sites — see docs/migrate.md for the mapping).",
           ""]
    out.extend(knob_section())
    for title, modname, names in SECTIONS:
        mod = importlib.import_module(modname)
        if title:
            out.append(f"## {title}")
            out.append("")
        if not names:
            para = _first_para(mod.__doc__)
            out.append(f"**module `{modname}`** — {para}")
            out.append("")
            continue
        out.append(f"*module `{modname}`*")
        out.append("")
        for n in names:
            obj = getattr(mod, n, None)
            if obj is None:
                continue
            doc = _first_para(getattr(obj, "__doc__", "") or "")
            if inspect.isclass(obj):
                out.append(f"- **`{n}`** (class) — {doc}")
            else:
                out.append(f"- **`{n}{_sig(obj)}`** — {doc}")
        out.append("")
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "api.md")
    with open(path, "w") as f:
        f.write("\n".join(out))
    print(f"wrote {path} ({len(out)} lines)")


if __name__ == "__main__":
    main()
