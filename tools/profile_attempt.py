"""Background attempt to capture a device trace of the ResNet step.

The axon tunnel may take minutes to set up profiling; run detached.
Output: /tmp/rn_trace (xplane + perfetto trace if successful).
"""
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp
import optax

sys.path.insert(0, "/root/repo")
from horovod_tpu.models.resnet import ResNet50  # noqa: E402


def fetch(x):
    return float(np.asarray(x).reshape(-1)[0])


def main():
    batch = 256
    m = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    v = m.init(jax.random.PRNGKey(0), jnp.ones((2, 224, 224, 3)), train=True)
    params, bstats = v["params"], v["batch_stats"]
    opt = optax.sgd(0.01, momentum=0.9)

    def loss_fn(p, b, im, lb):
        logits, mut = m.apply({"params": p, "batch_stats": b}, im, train=True,
                              mutable=["batch_stats"])
        logp = jax.nn.log_softmax(logits)
        return (-jnp.mean(jnp.take_along_axis(logp, lb[:, None], axis=1)),
                mut["batch_stats"])

    @jax.jit
    def step(p, b, o, im, lb):
        (l, nb), g = jax.value_and_grad(loss_fn, has_aux=True)(p, b, im, lb)
        u, o = opt.update(g, o, p)
        p = optax.apply_updates(p, u)
        return p, nb, o, l

    im = jnp.asarray(np.random.RandomState(0).rand(batch, 224, 224, 3),
                     jnp.float32)
    lb = jnp.zeros((batch,), jnp.int32)
    state = (params, bstats, opt.init(params))
    out = step(*state, im, lb)
    fetch(out[-1])
    out = step(*out[:-1], im, lb)
    fetch(out[-1])
    state = out[:-1]
    print("warmed up, starting trace", flush=True)
    jax.profiler.start_trace("/tmp/rn_trace")
    for _ in range(3):
        out = step(*state, im, lb)
        state = out[:-1]
    fetch(out[-1])
    print("steps done, stopping trace", flush=True)
    jax.profiler.stop_trace()
    print("trace complete", flush=True)


if __name__ == "__main__":
    main()
