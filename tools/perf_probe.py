"""Quick perf probe: raw-jit ResNet-50 train step MFU at various batch sizes.

Not part of the benchmark surface — a scratch tool for profile-driven tuning
(VERDICT r2 item 1). Run: python tools/perf_probe.py 128 256 512
"""
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp
import optax

sys.path.insert(0, ".")
from horovod_tpu.models.resnet import ResNet50  # noqa: E402

PEAK = 197.0  # v5e bf16
FLOPS_IMG = 3 * 4.1e9


def fetch(x):
    return float(np.asarray(x).reshape(-1)[0])


def probe(batch, iters=10):
    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    rng = jax.random.PRNGKey(0)
    images = jnp.asarray(np.random.RandomState(0).rand(batch, 224, 224, 3),
                         jnp.float32)
    labels = jnp.asarray(
        np.random.RandomState(1).randint(0, 1000, size=(batch,)), jnp.int32)
    variables = model.init(rng, images[:2], train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]
    opt = optax.sgd(0.01, momentum=0.9)

    def loss_fn(params, batch_stats, images, labels):
        logits, mutated = model.apply(
            {"params": params, "batch_stats": batch_stats}, images, train=True,
            mutable=["batch_stats"])
        logp = jax.nn.log_softmax(logits)
        loss = -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))
        return loss, mutated["batch_stats"]

    @jax.jit
    def step(params, batch_stats, opt_state, images, labels):
        (loss, new_bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, batch_stats, images, labels)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_bs, opt_state, loss

    state = (params, batch_stats, opt.init(params))
    out = step(*state, images, labels)
    fetch(out[-1])
    out = step(*out[:-1], images, labels)
    fetch(out[-1])
    state = out[:-1]
    # cost analysis
    try:
        ca = step.lower(*state, images, labels).compile().cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        xla_flops = float(ca.get("flops", 0.0))
    except Exception:
        xla_flops = 0.0
    t0 = time.perf_counter()
    for _ in range(iters):
        out = step(*state, images, labels)
        state = out[:-1]
    fetch(out[-1])
    dt = (time.perf_counter() - t0) / iters
    tflops = (xla_flops or FLOPS_IMG * batch) / dt / 1e12
    print(f"batch={batch:4d} step={dt*1e3:8.2f}ms img/s={batch/dt:9.1f} "
          f"xla_flops={xla_flops/1e12:.3f}T tflops={tflops:7.2f} "
          f"mfu={100*tflops/PEAK:5.1f}%", flush=True)


if __name__ == "__main__":
    for b in [int(a) for a in sys.argv[1:]] or [128, 256]:
        probe(b)
