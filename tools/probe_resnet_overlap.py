"""Overlap experiment for the ResNet-50 roofline (VERDICT r3 item 7).

docs/roofline.md establishes the step is HBM-bound: measured ~98 ms vs a
62 ms perfect-DMA/MXU-overlap floor. This probe measures the single-chip
train step under candidate XLA scheduler knobs (latency-hiding scheduler,
larger scoped VMEM for deeper fusion) to see whether scheduler-level levers
recover any of the overlap gap. Run once per flag set:

    python tools/probe_resnet_overlap.py                # baseline
    XLA_FLAGS="--xla_tpu_enable_latency_hiding_scheduler=true" \
        python tools/probe_resnet_overlap.py
    XLA_FLAGS="--xla_tpu_scoped_vmem_limit_kib=65536" \
        python tools/probe_resnet_overlap.py

Prints one line: flags + median step ms (dependent-steps timing, tunnel RTT
subtracted) so runs can be compared across the shared-chip noise band
(repeat >= 2x per flag set).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax

    from bench import _time_steps
    from horovod_tpu.models.resnet import ResNet50

    batch = int(os.environ.get("BENCH_BATCH", "128"))
    iters = int(os.environ.get("BENCH_ITERS", "20"))
    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    rng = jax.random.PRNGKey(0)
    images = jnp.asarray(np.random.RandomState(0).rand(batch, 224, 224, 3),
                         jnp.float32)
    labels = jnp.asarray(
        np.random.RandomState(1).randint(0, 1000, size=(batch,)), jnp.int32)
    variables = model.init(rng, images[:2], train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    def loss_fn(params, batch_stats, images, labels):
        logits, mutated = model.apply(
            {"params": params, "batch_stats": batch_stats}, images,
            train=True, mutable=["batch_stats"])
        logp = jax.nn.log_softmax(logits)
        loss = -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))
        return loss, mutated["batch_stats"]

    opt = optax.sgd(0.01, momentum=0.9)

    def step_fn(params, batch_stats, opt_state, images, labels):
        (loss, new_bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, batch_stats, images, labels)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_bs, opt_state, loss

    # XLA_FLAGS can't carry TPU-compiler flags on a remote-compile rig (the
    # client's parser rejects unknown flags before forwarding); per-compile
    # compiler options are the channel that reaches the TPU compiler.
    # PROBE_COMPILER_OPTIONS="xla_tpu_enable_latency_hiding_scheduler=true"
    opts_env = os.environ.get("PROBE_COMPILER_OPTIONS", "")
    copts = dict(kv.split("=", 1) for kv in opts_env.split(",") if "=" in kv)
    state = (params, batch_stats, opt.init(params))
    lowered = jax.jit(step_fn).lower(*state, images, labels)
    step = (lowered.compile(compiler_options=copts) if copts
            else lowered.compile())
    dt, rtt, _spread = _time_steps(step, state, (images, labels),
                                   iters)
    print(f"opts={opts_env!r} "
          f"step_ms={dt * 1e3:.2f} rtt_ms={rtt * 1e3:.1f} "
          f"img_s={batch / dt:.1f}", flush=True)


if __name__ == "__main__":
    main()
