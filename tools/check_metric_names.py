"""CLI shim for the metric-namespace lint.

The implementation lives in :mod:`horovod_tpu.analysis.metriccheck`
(ISSUE 15 folded it into the analysis package); ``tools/check.py`` runs
it next to the other lints. This entry point remains for single-lint
runs: ``python tools/check_metric_names.py``; exit code 0 means clean.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from horovod_tpu.analysis.metriccheck import (  # noqa: E402,F401
    VALID_TYPES, validate_specs)


def main() -> int:
    from horovod_tpu.analysis import metriccheck
    errors, stats = metriccheck.run()
    if errors:
        print(f"{len(errors)} metric declaration error(s):")
        for e in errors:
            print(f"  - {e}")
        return 1
    print(f"{stats['declared']} declared metrics OK "
          f"(^hvd_tpu_[a-z0-9_]+$, typed, documented)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
