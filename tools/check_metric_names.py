"""Lint the metrics namespace: every metric the framework declares must
match ``^hvd_tpu_[a-z0-9_]+$`` and carry a non-empty help string.

Thin shim: ``tools/check.py`` is the unified driver that runs this next
to the lockcheck/knob/fault/trace-schema lints (one tier-1 test,
tests/test_check.py). This entry point remains for single-lint runs:
``python tools/check_metric_names.py``; exit code 0 means clean. The
registry factories enforce the same rules at runtime for undeclared
names, but this check catches a bad declaration before anything ever
instantiates it.
"""

from __future__ import annotations

import os
import sys
from typing import Dict, List, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

VALID_TYPES = ("counter", "gauge", "histogram", "events")


def validate_specs(specs: Dict[str, Tuple[str, str]]) -> List[str]:
    """Return a list of error strings; empty means the table is clean."""
    from horovod_tpu.metrics import NAME_RE
    errors = []
    for name, spec in sorted(specs.items()):
        if not isinstance(spec, tuple) or len(spec) != 2:
            errors.append(f"{name}: spec must be a (type, help) tuple")
            continue
        kind, help_str = spec
        if not NAME_RE.match(name):
            errors.append(
                f"{name}: does not match {NAME_RE.pattern}")
        if kind not in VALID_TYPES:
            errors.append(f"{name}: unknown metric type {kind!r}")
        if not isinstance(help_str, str) or not help_str.strip():
            errors.append(f"{name}: missing help string")
        if kind == "counter" and not name.endswith("_total"):
            errors.append(
                f"{name}: counters must end in _total "
                f"(Prometheus naming convention)")
    return errors


def main() -> int:
    from horovod_tpu.metrics import METRIC_SPECS
    errors = validate_specs(METRIC_SPECS)
    if errors:
        print(f"{len(errors)} metric declaration error(s):")
        for e in errors:
            print(f"  - {e}")
        return 1
    print(f"{len(METRIC_SPECS)} declared metrics OK "
          f"(^hvd_tpu_[a-z0-9_]+$, typed, documented)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
