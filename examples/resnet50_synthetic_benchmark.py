"""ResNet-50 synthetic benchmark — the user-facing analog of the reference's
examples/tensorflow2_synthetic_benchmark.py (docs/benchmarks.rst:68-75).

SPMD flavor (default, TPU-idiomatic): one process drives every local chip
through a shard_map'd train step whose gradient reduction is the framework's
distributed optax wrapper.

    python examples/resnet50_synthetic_benchmark.py --batch-size 128

Eager flavor (one process per chip, Horovod-style):

    tpurun -np 4 python examples/resnet50_synthetic_benchmark.py --mode eager
"""

from __future__ import annotations

import argparse
import time

import numpy as np
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import horovod_tpu as hvd  # installs the jax compat shims first
from jax import shard_map
from horovod_tpu import optimizer as hvd_opt
from horovod_tpu.models.resnet import ResNet50


def parse_args():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=("spmd", "eager"), default="spmd")
    ap.add_argument("--batch-size", type=int, default=64,
                    help="per-chip batch size")
    ap.add_argument("--num-iters", type=int, default=10)
    ap.add_argument("--num-warmup", type=int, default=2)
    ap.add_argument("--fp16-allreduce", action="store_true",
                    help="compress eager-mode gradients to bf16 "
                         "(reference --fp16-allreduce)")
    return ap.parse_args()


def make_model_and_data(batch):
    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    rng = np.random.RandomState(0)
    images = jnp.asarray(rng.rand(batch, 224, 224, 3), jnp.float32)
    labels = jnp.asarray(rng.randint(0, 1000, size=(batch,)), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), images[:2], train=True)
    return model, variables, images, labels


def loss_fn(model, params, batch_stats, images, labels):
    logits, mutated = model.apply(
        {"params": params, "batch_stats": batch_stats}, images, train=True,
        mutable=["batch_stats"])
    logp = jax.nn.log_softmax(logits)
    loss = -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))
    return loss, mutated["batch_stats"]


def run_spmd(args):
    n_chips = len(jax.devices())
    mesh = Mesh(np.array(jax.devices()), ("data",))
    batch = args.batch_size * n_chips
    model, variables, images, labels = make_model_and_data(batch)
    params, batch_stats = variables["params"], variables["batch_stats"]
    images = jax.device_put(images, NamedSharding(mesh, P("data")))
    labels = jax.device_put(labels, NamedSharding(mesh, P("data")))

    opt = hvd_opt.distributed(optax.sgd(0.01, momentum=0.9),
                              axis_name="data", op=hvd.Average,
                              axis_size=n_chips)

    def body(params, batch_stats, opt_state, images, labels):
        (loss, new_bs), grads = jax.value_and_grad(
            lambda p, b: loss_fn(model, p, b, images, labels),
            has_aux=True)(params, batch_stats)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        new_bs = jax.tree_util.tree_map(
            lambda x: jax.lax.pmean(x, "data"), new_bs)
        return params, new_bs, opt_state, jax.lax.pmean(loss, "data")

    step = jax.jit(shard_map(body, mesh=mesh,
                             in_specs=(P(), P(), P(), P("data"), P("data")),
                             out_specs=(P(), P(), P(), P())))
    state = (params, batch_stats, opt.init(params))
    for _ in range(max(args.num_warmup, 2)):
        out = step(*state, images, labels)
        state = out[:-1]
        float(np.asarray(out[-1]))
    t0 = time.perf_counter()
    for _ in range(args.num_iters):
        out = step(*state, images, labels)
        state = out[:-1]
    float(np.asarray(out[-1]))
    dt = time.perf_counter() - t0
    img_s = batch * args.num_iters / dt
    print(f"Total img/sec on {n_chips} chip(s): {img_s:.1f} "
          f"({img_s / n_chips:.1f}/chip)")


def run_eager(args):
    hvd.init()
    model, variables, images, labels = make_model_and_data(args.batch_size)
    params, batch_stats = variables["params"], variables["batch_stats"]
    compression = (hvd.Compression.fp16 if args.fp16_allreduce
                   else hvd.Compression.none)
    opt = hvd.DistributedOptimizer(optax.sgd(0.01, momentum=0.9),
                                   op=hvd.Average, compression=compression)
    opt_state = opt.init(params)

    grad_fn = jax.jit(jax.value_and_grad(
        lambda p, b: loss_fn(model, p, b, images, labels), has_aux=True))

    def step(params, batch_stats, opt_state):
        (loss, new_bs), grads = grad_fn(params, batch_stats)
        params, opt_state = opt.update_and_apply(grads, opt_state, params)
        return params, new_bs, opt_state, loss

    state = (params, batch_stats, opt_state)
    for _ in range(max(args.num_warmup, 2)):
        out = step(*state)
        state = out[:-1]
        float(np.asarray(out[-1]))
    t0 = time.perf_counter()
    for _ in range(args.num_iters):
        out = step(*state)
        state = out[:-1]
    float(np.asarray(out[-1]))
    dt = time.perf_counter() - t0
    img_s = args.batch_size * args.num_iters / dt
    if hvd.rank() == 0:
        print(f"Img/sec per worker: {img_s:.1f}; "
              f"total ({hvd.size()} workers): {img_s * hvd.size():.1f}")
    hvd.shutdown()


if __name__ == "__main__":
    args = parse_args()
    if args.mode == "spmd":
        run_spmd(args)
    else:
        run_eager(args)
