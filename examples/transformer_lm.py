"""Flagship transformer-LM training example.

Three modes:

- ``--mode spmd`` (default): the TPU-idiomatic path — one process, all
  chips, the whole train step shard_mapped over a (data, seq, tensor)
  mesh built from ``--mesh data=2,seq=2,tensor=2`` (axes riding DCN go
  first; see ``horovod_tpu.parallel.mesh.multislice_mesh`` for
  multi-slice pods). ``--sp-layout zigzag`` load-balances the causal
  ring.
- ``--mode eager``: the Horovod-style path — one process per chip under
  ``tpurun``, gradients reduced through ``hvd.DistributedOptimizer``
  (``--delta-adasum`` for the delta-model Adasum form).
- ``--mode pp``: the flagship through the memory-bounded 1F1B pipeline
  (``--stages``, ``--n-micro``).

Synthetic data; prints tokens/sec. Mirrors the reference's synthetic
benchmark scripts (examples/*_synthetic_benchmark.py) for the LM workload.
"""

from __future__ import annotations

import argparse
import time


def parse_mesh(spec: str) -> dict:
    out = {}
    for part in spec.split(","):
        k, v = part.split("=")
        out[k.strip()] = int(v)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=["spmd", "eager", "pp"],
                    default="spmd")
    ap.add_argument("--stages", type=int, default=None,
                    help="pp mode: pipeline stages (default: all devices)")
    ap.add_argument("--n-micro", type=int, default=4,
                    help="pp mode: microbatches per step")
    ap.add_argument("--mesh", default=None,
                    help="e.g. data=2,seq=2,tensor=2 (spmd mode)")
    ap.add_argument("--d-model", type=int, default=512)
    ap.add_argument("--n-layers", type=int, default=4)
    ap.add_argument("--n-heads", type=int, default=8)
    ap.add_argument("--d-ff", type=int, default=2048)
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--batch", type=int, default=8, help="global batch")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--attention", default="ring",
                    choices=["ring", "ulysses", "flash"])
    ap.add_argument("--sp-layout", default="contiguous",
                    choices=["contiguous", "zigzag"],
                    help="sequence-parallel data layout; zigzag balances "
                         "causal ring work exactly across ranks (tokens/"
                         "targets are permuted with zigzag_indices here)")
    ap.add_argument("--moe", action="store_true")
    ap.add_argument("--delta-adasum", action="store_true",
                    help="eager mode: delta-model Adasum (local optimizer "
                         "step first, Adasum on the parameter delta)")
    args = ap.parse_args()

    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from horovod_tpu.models.transformer import (TransformerConfig,
                                                init_params, lean_lm_loss,
                                                make_train_step,
                                                shard_params)

    cfg = TransformerConfig(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
        n_layers=args.n_layers, d_ff=args.d_ff, max_seq=args.seq,
        dtype=jnp.bfloat16, attention=args.attention,
        sp_layout=args.sp_layout, use_moe=args.moe)
    opt = optax.adamw(3e-4)
    rng = np.random.RandomState(0)
    # seq+1 raw tokens so the shifted input/target windows are exactly
    # --seq long (keeps sequence sharding divisible)
    tokens = rng.randint(0, args.vocab, size=(args.batch, args.seq + 1))
    inputs = jnp.asarray(tokens[:, :-1])
    targets = jnp.asarray(tokens[:, 1:])

    if args.mode == "pp":
        # the flagship through the memory-bounded 1F1B pipeline: embedding
        # on stage 0, n_layers/stages layers per stage, tied-embedding
        # head + lean loss on the last stage (docs/parallelism.md)
        from jax.sharding import Mesh
        from horovod_tpu.models.transformer import (make_pp_train_step,
                                                    pp_param_specs)
        n_stages = args.stages or len(jax.devices())
        mesh = Mesh(np.array(jax.devices()[:n_stages]), ("pipe",))
        specs = pp_param_specs(cfg)
        params = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            init_params(jax.random.PRNGKey(0), cfg), specs)
        step = make_pp_train_step(mesh, cfg, opt, n_micro=args.n_micro)
        opt_state = opt.init(params)
        params, opt_state, loss = step(params, opt_state, inputs, targets)
        t0 = time.perf_counter()
        for _ in range(args.steps):
            params, opt_state, loss = step(params, opt_state, inputs,
                                           targets)
        loss = float(loss)
        dt = (time.perf_counter() - t0) / args.steps
    elif args.mode == "spmd":
        from horovod_tpu.parallel.mesh import training_mesh
        # the flagship step names all three axes; absent ones get size 1
        mesh_spec = {"data": len(jax.devices()), "seq": 1, "tensor": 1}
        if args.mesh:
            mesh_spec.update({"data": 1})
            mesh_spec.update(parse_mesh(args.mesh))
        mesh = training_mesh(mesh_spec)
        params = shard_params(init_params(jax.random.PRNGKey(0), cfg),
                              mesh, cfg)
        step = make_train_step(mesh, cfg, opt)
        opt_state = opt.init(params)
        tok_sh = NamedSharding(mesh, P("data", "seq"))
        if args.sp_layout == "zigzag":
            # zigzag data layout: the model is layout-transparent (no
            # positional encoding; per-token loss mean is permutation-
            # invariant), only the tokens must be permuted to match
            from horovod_tpu.parallel import zigzag_indices
            idx, _ = zigzag_indices(args.seq, mesh_spec.get("seq", 1))
            inputs = jnp.take(inputs, idx, axis=1)
            targets = jnp.take(targets, idx, axis=1)
        inputs = jax.device_put(inputs, tok_sh)
        targets = jax.device_put(targets, tok_sh)
        params, opt_state, loss = step(params, opt_state, inputs, targets)
        t0 = time.perf_counter()
        for _ in range(args.steps):
            params, opt_state, loss = step(params, opt_state, inputs,
                                           targets)
        loss = float(loss)
        dt = (time.perf_counter() - t0) / args.steps
    else:
        import horovod_tpu as hvd
        hvd.init()
        if args.delta_adasum:
            # delta-model Adasum (torch/optimizer.py:196-364): local
            # optimizer step first, scale-invariant VHDD on the delta
            opt = hvd.DistributedDeltaAdasumOptimizer(opt)
        else:
            opt = hvd.DistributedOptimizer(opt, op=hvd.Average)
        params = init_params(jax.random.PRNGKey(0), cfg)
        params = hvd.broadcast_parameters(params, root_rank=0)
        opt_state = opt.init(params)
        grad_fn = jax.jit(jax.value_and_grad(
            lambda p, x, y: lean_lm_loss(p, x, y, cfg)))
        # per-rank shard of the global batch
        per = max(args.batch // hvd.size(), 1)
        lo = hvd.rank() * per
        bx, by = inputs[lo:lo + per], targets[lo:lo + per]
        loss, grads = grad_fn(params, bx, by)
        params, opt_state = opt.update_and_apply(grads, opt_state, params)
        t0 = time.perf_counter()
        for _ in range(args.steps):
            loss, grads = grad_fn(params, bx, by)
            params, opt_state = opt.update_and_apply(grads, opt_state,
                                                     params)
        loss = float(loss)
        dt = (time.perf_counter() - t0) / args.steps

    toks = args.batch * args.seq
    print({"mode": args.mode, "loss": round(loss, 4),
           "step_ms": round(dt * 1e3, 2),
           "tokens_per_sec": round(toks / dt, 1)})


if __name__ == "__main__":
    main()
