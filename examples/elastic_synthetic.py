"""Elastic training example — analog of the reference's
examples/elastic/pytorch_synthetic_benchmark_elastic.py.

Run with a discovery script whose output can change while the job runs:

    tpurun -np 2 --min-np 1 --max-np 4 \
        --host-discovery-script ./discover_hosts.sh \
        python examples/elastic_synthetic.py

State (model params, optimizer state, batch counter) is committed every
``--batches-per-commit`` batches; on membership change or worker failure the
job restores the last commit and continues at the new world size.
"""

from __future__ import annotations

import argparse

import numpy as np
import jax
import jax.numpy as jnp
import optax

import horovod_tpu as hvd
from horovod_tpu.models.mlp import init_mlp, mlp_forward, softmax_cross_entropy


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--total-batches", type=int, default=500)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--batches-per-commit", type=int, default=10)
    ap.add_argument("--lr", type=float, default=1e-3)
    args = ap.parse_args()

    hvd.init()

    opt = optax.adam(args.lr)
    dist_opt = hvd.DistributedOptimizer(opt, op=hvd.Average)
    params = init_mlp(jax.random.PRNGKey(0), sizes=(256, 128, 10))
    opt_state = dist_opt.init(params)

    # TPUState keeps host-RAM copies of the pytrees on commit() and
    # broadcast-syncs them to new/restored workers (reference:
    # hvd.elastic.TorchState).
    state = hvd.elastic.TPUState(params=params, opt_state=opt_state, batch=0)

    @jax.jit
    def grad_fn(params, x, y):
        return jax.value_and_grad(
            lambda p: softmax_cross_entropy(mlp_forward(p, x), y))(params)

    @hvd.elastic.run
    def train(state):
        rng = np.random.RandomState(100 + hvd.rank())
        while state.batch < args.total_batches:
            x = jnp.asarray(rng.rand(args.batch_size, 256), jnp.float32)
            y = jnp.asarray(rng.randint(0, 10, size=(args.batch_size,)),
                            jnp.int32)
            loss, grads = grad_fn(state.params, x, y)
            state.params, state.opt_state = dist_opt.update_and_apply(
                grads, state.opt_state, state.params)
            state.batch += 1
            if state.batch % args.batches_per_commit == 0:
                state.commit()
                if hvd.rank() == 0 and state.batch % 100 == 0:
                    print(f"batch {state.batch}: loss={float(loss):.4f} "
                          f"size={hvd.size()}")
        return float(loss)

    final_loss = train(state)
    if final_loss is not None and hvd.rank() == 0:
        print(f"done: final loss {final_loss:.4f} at size {hvd.size()}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
