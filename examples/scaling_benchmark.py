"""Scaling-efficiency harness (SURVEY §7 Slice 7; BASELINE.md's
allreduce-scaling-efficiency 8→256-chip metric; reference
docs/benchmarks.rst:7-13 measured 90% at 512 GPUs).

For each world size n (sub-meshes of the available devices — real chips on a
pod, or the forced-host CPU world for harness validation):

- **allreduce bus bandwidth**: fused ring-allreduce of a fixed per-chip
  buffer; algorithmic bandwidth = 2·(n−1)/n · bytes / time.
- **weak-scaling efficiency**: a data-parallel train step at fixed per-chip
  batch; efficiency(n) = throughput(n) / (n · throughput(1)).

Prints one JSON line per (size, measurement).

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/scaling_benchmark.py --sizes 1,2,4,8
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def _fetch(x):
    """Completion barrier: pull ONE element to the host (materializing the
    whole buffer would add a size-dependent D2H transfer to the timed
    window)."""
    return float(np.asarray(x.ravel()[0:1])[0])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", default=None,
                    help="comma list of world sizes (default: 1,2,4,...,N)")
    ap.add_argument("--bytes", type=int, default=64 * 1024 * 1024,
                    help="allreduce buffer size per chip")
    ap.add_argument("--batch-per-chip", type=int, default=64)
    ap.add_argument("--iters", type=int, default=10)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import horovod_tpu  # installs the jax compat shims first
    from jax import shard_map

    from horovod_tpu import optimizer as hvd_opt
    from horovod_tpu.common.reduce_ops import Average, ReduceOp
    from horovod_tpu.models.mlp import (init_mlp, mlp_forward,
                                        softmax_cross_entropy)
    from horovod_tpu.ops.collectives import build_allreduce

    devices = jax.devices()
    n_dev = len(devices)
    if args.sizes:
        requested = [int(s) for s in args.sizes.split(",")]
        sizes = [s for s in requested if s <= n_dev]
        dropped = [s for s in requested if s > n_dev]
        if dropped:
            print(f"warning: dropping sizes {dropped} (> {n_dev} devices)",
                  file=__import__("sys").stderr)
        if not sizes:
            raise SystemExit(
                f"no requested world size fits the {n_dev} visible devices")
        sizes = sorted(set(sizes))   # efficiency baseline must run first
    else:
        sizes = [s for s in (2 ** i for i in range(n_dev.bit_length()))
                 if s <= n_dev]

    n_elems = args.bytes // 4
    base_throughput = None
    for n in sizes:
        mesh = Mesh(np.array(devices[:n]), ("data",))

        # -- allreduce bandwidth (through the framework's builder, so the
        # metric certifies the framework path, not raw XLA) ---------------
        buf = jax.device_put(
            jnp.ones((n, n_elems), jnp.float32),
            NamedSharding(mesh, P("data")))
        ar = build_allreduce(mesh, "data", ReduceOp.SUM)
        out = ar(buf)
        _fetch(out)
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out = ar(buf)
        _fetch(out)
        dt = (time.perf_counter() - t0) / args.iters
        busbw = 2 * (n - 1) / n * args.bytes / dt if n > 1 else 0.0
        print(json.dumps({
            "bench": "allreduce", "world": n,
            "bytes_per_chip": args.bytes,
            "time_ms": round(dt * 1e3, 3),
            "algo_busbw_gbps": round(busbw / 1e9, 3),
        }))

        # -- weak-scaling train step ------------------------------------
        batch = args.batch_per_chip * n
        rng = np.random.RandomState(0)
        x = jax.device_put(jnp.asarray(rng.rand(batch, 784), jnp.float32),
                           NamedSharding(mesh, P("data")))
        y = jax.device_put(jnp.asarray(rng.randint(0, 10, size=(batch,)),
                                       jnp.int32),
                           NamedSharding(mesh, P("data")))
        params = init_mlp(jax.random.PRNGKey(0))
        opt = hvd_opt.distributed(optax.sgd(0.01), axis_name="data",
                                  op=Average, axis_size=n)

        def body(params, opt_state, x, y):
            loss, grads = jax.value_and_grad(
                lambda p: softmax_cross_entropy(mlp_forward(p, x), y))(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, \
                jax.lax.pmean(loss, "data")

        step = jax.jit(shard_map(body, mesh=mesh,
                                 in_specs=(P(), P(), P("data"), P("data")),
                                 out_specs=(P(), P(), P())))
        state = (params, opt.init(params))
        for _ in range(2):
            out = step(*state, x, y)
            state = out[:-1]
            _fetch(out[-1])
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out = step(*state, x, y)
            state = out[:-1]
        _fetch(out[-1])
        dt = (time.perf_counter() - t0) / args.iters
        throughput = batch / dt
        if n == min(sizes):
            base_throughput = throughput / n
        # efficiency is relative to the SMALLEST measured size (==1 when
        # present, matching the docstring formula)
        eff = throughput / (n * base_throughput) if base_throughput else None
        print(json.dumps({
            "bench": "weak_scaling_train", "world": n,
            "batch_per_chip": args.batch_per_chip,
            "samples_per_sec": round(throughput, 1),
            "scaling_efficiency": round(eff, 4) if eff else None,
        }))


if __name__ == "__main__":
    main()
