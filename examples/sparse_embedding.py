"""Embedding-table training with sparse gradient reduction.

Analog of the reference's IndexedSlices / sparse-gradient handling inside
the optimizer (tensorflow/__init__.py:52-131, torch sparse grads): an
embedding model's gradient is dense under JAX but touches only the rows of
the tokens in the batch. Marking the leaf with ``sparse_rows`` ships the
top-k touched rows as (indices, values) allgathers — wire bytes scale with
tokens-per-batch instead of vocabulary size — and recombines them with a
jitted on-device scatter-add.

Run single-process:   python examples/sparse_embedding.py
Run multi-process:    tpurun -np 2 python examples/sparse_embedding.py
"""

from __future__ import annotations

import argparse
import time

import numpy as np
import jax
import jax.numpy as jnp
import optax

import horovod_tpu as hvd
from horovod_tpu.optimizer import DistributedEagerOptimizer


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--vocab", type=int, default=50_000)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--steps", type=int, default=50)
    args = ap.parse_args()

    hvd.init()
    rank, size = hvd.rank(), hvd.size()

    params = {
        "embed": jnp.asarray(
            np.random.RandomState(0).randn(args.vocab, args.dim) * 0.02,
            jnp.float32),
        "proj": jnp.asarray(np.eye(args.dim), jnp.float32),
    }
    params = hvd.broadcast_parameters(params, root_rank=0)
    # The "embed" grad leaf touches at most batch_size rows per step; the
    # dense table never crosses the wire. Everything else reduces densely.
    opt = DistributedEagerOptimizer(
        optax.adagrad(0.1), op=hvd.Average,
        sparse_rows={"embed": args.batch_size})
    opt_state = opt.init(params)

    def loss_fn(p, tok, tgt):
        h = p["embed"][tok] @ p["proj"]
        return jnp.mean((h - tgt) ** 2)

    grad_fn = jax.jit(jax.grad(loss_fn))
    rng = np.random.RandomState(100 + rank)
    t0 = time.perf_counter()
    for step in range(args.steps):
        tok = jnp.asarray(rng.randint(0, args.vocab, args.batch_size))
        tgt = jnp.asarray(rng.randn(args.batch_size, args.dim)
                          .astype(np.float32))
        grads = grad_fn(params, tok, tgt)
        # chained: the jitted update rides the reduced-rows futures with
        # no host block; the top-k extraction + scatter-add are jitted
        params, opt_state = opt.update_and_apply(grads, opt_state, params)
    jax.block_until_ready(params)
    dt = time.perf_counter() - t0
    dense_bytes = args.vocab * args.dim * 4
    sparse_bytes = args.batch_size * (args.dim + 1) * 4
    if rank == 0:
        print(f"size={size} steps={args.steps} "
              f"({dt / args.steps * 1e3:.2f} ms/step); per-step embed wire: "
              f"{sparse_bytes / 1e3:.0f} KB sparse vs "
              f"{dense_bytes / 1e6:.1f} MB dense "
              f"({dense_bytes / sparse_bytes:.0f}x saved)")
    hvd.shutdown()


if __name__ == "__main__":
    main()
