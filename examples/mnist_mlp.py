"""MNIST-style MLP training with the eager (Horovod-style) API.

Analog of the reference's examples/tensorflow2_mnist.py: init the runtime,
broadcast initial parameters from rank 0, wrap the optimizer so gradients are
averaged across workers, scale the learning rate by world size.

Run single-process:   python examples/mnist_mlp.py
Run multi-process:    tpurun -np 2 python examples/mnist_mlp.py

Uses synthetic MNIST-shaped data so the example runs hermetically (no
download); swap `synthetic_mnist` for a real loader in production.
"""

from __future__ import annotations

import argparse
import time

import numpy as np
import jax
import jax.numpy as jnp
import optax

import horovod_tpu as hvd
from horovod_tpu.models.mlp import init_mlp, mlp_forward, softmax_cross_entropy


def synthetic_mnist(rank: int, n: int = 4096):
    rng = np.random.RandomState(1234 + rank)  # each rank gets its own shard
    x = rng.rand(n, 784).astype(np.float32)
    y = rng.randint(0, 10, size=(n,)).astype(np.int32)
    return x, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--lr", type=float, default=1e-3)
    args = ap.parse_args()

    hvd.init()

    # Scale the learning rate by world size (reference examples do the same).
    opt = optax.adam(args.lr * hvd.size())
    # DistributedOptimizer: gradients are fused + averaged across workers
    # between grad() and the optax update.
    dist_opt = hvd.DistributedOptimizer(opt, op=hvd.Average)

    params = init_mlp(jax.random.PRNGKey(42))
    # All workers start from rank 0's weights (reference:
    # broadcast_parameters / BroadcastGlobalVariablesCallback).
    params = hvd.broadcast_parameters(params, root_rank=0)
    opt_state = dist_opt.init(params)

    @jax.jit
    def grad_fn(params, x, y):
        def loss(p):
            return softmax_cross_entropy(mlp_forward(p, x), y)
        return jax.value_and_grad(loss)(params)

    x, y = synthetic_mnist(hvd.rank())
    steps_per_epoch = len(x) // args.batch_size
    for epoch in range(args.epochs):
        t0 = time.perf_counter()
        last_loss = None
        for step in range(steps_per_epoch):
            lo = step * args.batch_size
            bx, by = x[lo:lo + args.batch_size], y[lo:lo + args.batch_size]
            loss, grads = grad_fn(params, bx, by)
            params, opt_state = dist_opt.update_and_apply(grads, opt_state,
                                                          params)
            last_loss = loss
        dt = time.perf_counter() - t0
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss={float(last_loss):.4f} "
                  f"({steps_per_epoch / dt:.1f} steps/s, size={hvd.size()})")

    hvd.shutdown()


if __name__ == "__main__":
    main()
